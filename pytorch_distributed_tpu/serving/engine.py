"""Persistent donated-KV decode engine: the serving fast path.

The monolithic ``generate`` programs (models/decode.py) are the wrong
shape for a serving loop: the KV cache is jit-internal (re-allocated and
re-zeroed every request), every distinct prompt length compiles a fresh
prefill+loop program, and — before this PR — every sampling config change
recompiled too. ``DecodeEngine`` restructures generation into two
long-lived compiled programs, the shape TPU serving practice settles on
(Fine-Tuning and Serving Gemma on Cloud TPU; the pjit-scaling playbook —
PAPERS.md):

- ``prefill(params, prompt, prompt_len, cache, t, k, p, key)``
  runs the whole (bucket-padded) prompt and samples the first token;
- ``decode_run(params, tok, cache, pos, n, t, k, p, key)``
  runs n single-token steps in one dispatch (a fori_loop with a TRACED
  trip count — one compile covers every generation length);
- ``decode_step(...)`` is the single-step form behind ``stream()``.

Three levers, all machine-checked:

1. **Buffer donation**: the cache is ``donate_argnums``-donated through
   every program, and the engine keeps the returned buffer in a pool —
   steady-state serving allocates and zero-fills NOTHING per request.
   Reusing a dirty buffer is sound because decode's cache discipline
   (models/decode.py) masks key positions > pos and overwrites each row
   before it becomes readable; tests/test_serving.py pins it, including
   the GQA edge. Donation is verified to actually alias in the compiled
   executable (``verify_donation`` + the strict mode of
   analysis/audit.check_donation) — a silently-rejected alias would
   double-buffer the largest tensor in the server.
2. **Bounded compilation**: prompts are padded to a small set of
   ``BucketSpec`` lengths (default powers of two), so steady-state
   serving compiles O(buckets) prefill programs + ONE decode program —
   not O(requests). Sampling params are traced scalars
   (decode.sampling_scalars); only greedy-vs-sampled is static.
3. **Comm/compute overlap (ZeRO-3 mode)**: decode from full-shard
   training layouts routes the layer scan through
   ops/layer_scan.scan_layers's windowed double-buffer schedule
   (``MeshConfig.prefetch_buffers``), so layer l+1's param all-gathers
   stream in under layer l's compute — the decode-side twin of the
   explicit training path's prefetch (closes ROADMAP PR-3 follow-up (c)).

Modes (one engine per mode x config):
- plain: single device, whole params.
- tp (``mesh_cfg.tensor`` > 1): shard_map over a "tensor" mesh, Megatron
  layouts, local-head cache shards (the cache pytree is a GLOBAL array
  sharded over the head dim — 1/tp of the cache HBM per chip).
- zero3 (``mesh_cfg.fsdp`` > 1, full_shard): auto-partitioned decode in
  the ZeRO-3 training layout with the windowed gather schedule above.
TP x ZeRO-3 mixed meshes are rejected up front with a diagnostic naming
these modes (``_reject_tp_zero3_mix``); native composition is future
surface.

Two engines share this machinery:
- ``DecodeEngine`` — serial: one request (of any batch) at a time, with
  an LRU-BOUNDED dirty-cache pool across requests.
- ``BatchedDecodeEngine`` — continuous batching: a fixed pool of slot
  ROWS inside one (slots, max_len) cache, a host-side scheduler that
  admits/retires requests per row, per-row traced positions and sampling
  state, and ONE compiled decode step advancing every row per dispatch.
  See its class docstring; this is the engine that fills the batch
  dimension under real multi-tenant traffic.

Outputs are bit-equal to the monolithic reference paths for identical
requests (greedy and fixed-key sampled) — same forward, same sampler,
same key-folding schedule; padded prompt rows and pooled-buffer garbage
are masked out of every reduction. Pinned by tests/test_serving.py.

Not thread-safe: the cache pool hands the SAME buffer to concurrent
requests of one batch size. Serialise requests per engine (or shard
engines per worker).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig
from pytorch_distributed_tpu.models import decode
from pytorch_distributed_tpu.ops.quant import quantize_decode_params
from pytorch_distributed_tpu.serving.lifecycle import (
    ABORTED,
    DONE,
    EXPIRED,
    FAILED,
    AdmissionQueueFull,
    DispatchFailure,
    EngineSnapshot,
    RequestFailed,
    RequestResult,
)
from pytorch_distributed_tpu.serving.scheduler import (
    BATCH,
    INTERACTIVE,
    PRIORITIES,
    STANDARD,
    TIER_NAME,
    TIER_RANK,
    check_priority,
    preemption_key,
    queue_key,
)
from pytorch_distributed_tpu.utils.logging import log_event

_PROGRAM_KINDS = ("prefill", "decode_run", "decode_step")
_BATCHED_PROGRAM_KINDS = ("prefill", "decode_step", "decode_spec_step")
# Disaggregation-only paged programs: gather a row's KV pages off a
# PREFILL worker's pool / scatter them into a DECODE worker's (the
# kv_handoff wire path). Never dispatched by the tick scheduler.
_KV_PROGRAM_KINDS = ("kv_export", "kv_import")
_EMPTY_DRAFT = np.zeros((0,), np.int32)


def _kv_bytes_per_position(cfg: ModelConfig, kv_quant: str = "none") -> int:
    """K+V bytes one GLOBAL cache position costs across all layers (TP
    divides the head dim across shards, so the global figure is the
    comparable one either way). int8 pages carry one f32 scale per
    token per KV head next to the values (ops/quant.quantize_kv), so a
    quantized position costs head_dim + 4 bytes per head instead of
    head_dim x itemsize."""
    if kv_quant == "int8":
        return cfg.n_layer * 2 * cfg.kv_heads * (cfg.head_dim + 4)
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return cfg.n_layer * 2 * cfg.kv_heads * cfg.head_dim * itemsize


def _check_quant_arg(name: str, value: str) -> str:
    if value not in ("none", "int8"):
        raise ValueError(
            f"{name} must be 'none' or 'int8', got {value!r}"
        )
    return value


def _quantized_mesh_specs(cfg: ModelConfig, mesh, p_specs):
    """(quantized spec tree, quantized NamedSharding tree) for a
    weight-quantized decode params tree: kernel specs ride ``q8``,
    scale specs drop the contracting dim (ops/quant.quantized_param_specs
    — column-parallel scales shard with their channels, row-parallel
    scales replicate)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.models import get_model
    from pytorch_distributed_tpu.ops.quant import quantized_param_specs

    abstract = jax.eval_shape(
        lambda k: get_model(cfg).init(k, cfg), jax.random.key(0)
    )
    q_specs = quantized_param_specs(p_specs, abstract)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), q_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return q_specs, shardings


def _spec_accept_rate(counters: dict[str, int]) -> float | None:
    """accepted/drafted over the engine's lifetime — None until the
    first draft (and forever on engines that never speculate), so a
    dashboard can tell "speculation off/idle" from "0% accepts"."""
    drafted = counters.get("drafted_tokens", 0)
    if not drafted:
        return None
    return round(counters.get("accepted_tokens", 0) / drafted, 4)


def _reject_tp_zero3_mix(mesh_cfg: MeshConfig | None, entry: str) -> None:
    """Both serving entry points reject the TP x ZeRO-3 mixed mesh with
    one diagnostic naming the supported modes (ROADMAP serving follow-up
    (c)): decoding from a mixed layout needs each gathered layer window
    re-split over the tensor axis inside the token loop — a schedule
    neither the shard_map TP path nor the auto-partitioned ZeRO-3 path
    expresses today. Full composition is future surface."""
    if mesh_cfg is not None and mesh_cfg.tensor > 1 and mesh_cfg.fsdp > 1:
        raise NotImplementedError(
            f"{entry} does not support TP x ZeRO-3 mixed-mesh decode "
            f"(got tensor={mesh_cfg.tensor}, fsdp={mesh_cfg.fsdp}). "
            "Supported modes: plain (single device / no mesh), tp "
            "(tensor-only mesh, Megatron layouts with a head-sharded KV "
            "cache), and zero3 (fsdp-only full_shard mesh, DecodeEngine "
            "only). Serve a mixed-mesh checkpoint by resharding to one "
            "of those layouts; native composition is a future PR."
        )


def _select_mode(
    cfg: ModelConfig, mesh_cfg: MeshConfig | None, *,
    entry: str, allow_zero3: bool = True,
):
    """Shared engine mode selection: (mode, mesh_cfg, n_kv,
    prefetch_buffers), with the mixed-mesh rejection applied first so
    both engines emit the same diagnostic."""
    _reject_tp_zero3_mix(mesh_cfg, entry)
    if mesh_cfg is None or mesh_cfg.num_devices == 1:
        return "plain", None, None, 0
    if mesh_cfg.tensor > 1:
        decode._validate_tp_mesh(cfg, mesh_cfg)
        return "tp", mesh_cfg, cfg.kv_heads // mesh_cfg.tensor, 0
    if not allow_zero3:
        raise NotImplementedError(
            f"{entry} supports plain and tp modes; ZeRO-3 slot-batched "
            "decode is future surface — serve ZeRO-3 layouts through "
            "DecodeEngine, or decode from a tensor-only mesh"
        )
    decode._validate_fsdp_mesh(mesh_cfg)
    return "zero3", mesh_cfg, None, mesh_cfg.prefetch_buffers


# Disaggregated-serving roles (uniform ``stats()["role"]`` vocabulary).
# ``colocated`` engines run prefill AND decode (the historic behaviour);
# ``prefill`` workers run chunked prefill only and hand finished KV
# state off; ``decode`` workers accept handoffs/adoptions and run the
# decode tick only. Role is pure host-side scheduling — every role runs
# the SAME compiled programs (plus the kv transfer programs), so pinned
# budgets and compile counts are role-invariant.
ENGINE_ROLES = ("colocated", "prefill", "decode")


def _check_role(role: str) -> str:
    if role not in ENGINE_ROLES:
        raise ValueError(
            f"role must be one of {ENGINE_ROLES}, got {role!r}"
        )
    return role


def _resolve_device(device):
    """Resolve an int device id (or a ``jax.Device``) to the Device
    object, validating it exists on this process. The single-device
    engines take ``device=`` so a serving fleet can pin each replica to
    its own chip instead of every replica landing on the default
    device; meshed engines place via ``MeshConfig.device_ids``."""
    if device is None:
        return None
    if not isinstance(device, (int, np.integer)):
        return device  # already a jax.Device
    for d in jax.devices():
        if d.id == int(device):
            return d
    raise ValueError(
        f"device id {device} not present among jax.devices() ids "
        f"{sorted(d.id for d in jax.devices())}"
    )


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Prompt-length buckets. A request of length T compiles (at most)
    the program of the smallest bucket >= T; ``()`` means exact-length
    (no padding — one compile per distinct length, the compat-shim
    behaviour)."""

    buckets: tuple[int, ...] = ()

    def __post_init__(self) -> None:
        b = tuple(self.buckets)
        if any(x <= 0 for x in b) or list(b) != sorted(set(b)):
            raise ValueError(
                f"buckets must be strictly increasing positives, got {b}"
            )
        object.__setattr__(self, "buckets", b)

    @classmethod
    def powers_of_two(
        cls, max_len: int, min_bucket: int = 128
    ) -> "BucketSpec":
        """128/256/.../max_len (first bucket = min_bucket clipped to
        max_len; max_len itself is always the last bucket so every
        admissible prompt has a home)."""
        if min_bucket <= 0 or max_len <= 0:
            raise ValueError("min_bucket and max_len must be positive")
        out = []
        b = min_bucket
        while b < max_len:
            out.append(b)
            b *= 2
        out.append(max_len)
        return cls(tuple(out))

    def bucket_for(self, length: int) -> int:
        if not self.buckets:
            return length
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )


class DecodeEngine:
    """See module docstring. Construct once per (cfg, max_len, bucket
    spec, mesh); call ``generate`` / ``stream`` per request with any
    params matching ``cfg`` (params are call arguments, not engine state,
    so one engine serves many checkpoints of one architecture)."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_len: int,
        buckets: BucketSpec | None = None,
        mesh_cfg: MeshConfig | None = None,
        pool_caches: bool = True,
        pool_max_entries: int = 8,
        nan_guard: bool = True,
        weight_quant: str = "none",
        device: int | None = None,
    ) -> None:
        if max_len > cfg.n_ctx:
            raise ValueError(
                f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}"
            )
        self.cfg = cfg
        self.max_len = int(max_len)
        self.buckets = buckets or BucketSpec()
        if self.buckets.buckets and self.buckets.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket {self.buckets.buckets[-1]} exceeds "
                f"max_len {max_len}"
            )
        self.mode, self.mesh_cfg, self._n_kv, self._prefetch_buffers = (
            _select_mode(cfg, mesh_cfg, entry="DecodeEngine")
        )
        self.device = _resolve_device(device)
        if self.device is not None and self.mode != "plain":
            raise ValueError(
                "device= pins the single-device (plain) engine to one "
                "chip; meshed modes place via MeshConfig.device_ids"
            )
        self.weight_quant = _check_quant_arg("weight_quant", weight_quant)
        if self.weight_quant != "none" and self.mode == "zero3":
            raise NotImplementedError(
                "weight_quant with ZeRO-3 decode is future surface: the "
                "windowed layer gathers move full-precision shards and "
                "re-splitting int8+scale leaves through the auto "
                "partitioner is unproven — serve quantized weights from "
                "plain or tensor-only meshes"
            )
        if self.weight_quant != "none" and cfg.n_experts:
            raise NotImplementedError(
                "weight_quant does not cover MoE expert stacks (routed "
                "expert weights need per-expert calibration surface) — "
                "quantized decode serves dense gpt2/llama configs"
            )
        if self.mode != "plain":
            (
                self._mesh, self._p_specs, self._param_shardings
            ) = decode._mesh_param_shardings(cfg, self.mesh_cfg)
            if self.weight_quant != "none":
                self._p_specs, self._param_shardings = (
                    _quantized_mesh_specs(cfg, self._mesh, self._p_specs)
                )
        # (source tree, prepared tree): weight quantization runs ONCE per
        # params tree (identity memo), not once per request.
        self._prepared: tuple[Any, Any] | None = None
        # Pool HBM high-water mark (pooled + the in-flight buffer at the
        # moment it is taken) — cache_hbm_bytes' peak figure.
        self._peak_cache_bytes = 0
        # (kind, sampled) -> jitted program. Prefill additionally
        # specialises per bucket shape through jit's own shape cache, so
        # compile_count() reads len(buckets)-many entries off ONE program.
        self._programs: dict[tuple[str, bool], Any] = {}
        # batch -> dirty-but-reusable donated cache buffer. pool_caches
        # False (the compat shims) frees the cache after each request
        # instead — a shim engine exists per (cfg, max_len, mesh) and
        # lives forever in shim_engine's cache, so pooling there would
        # pin one full-size cache per distinct request shape; a real
        # serving deployment constructs ONE engine and wants the pool.
        # The pool is LRU-BOUNDED at pool_max_entries distinct batch
        # shapes (ROADMAP serving follow-up (d)): a traffic mix cycling
        # through many batch sizes caps pooled-cache HBM at
        # pool_max_entries x max_len-cache bytes instead of growing with
        # shape diversity; the least-recently-returned shape is dropped
        # (freed by the allocator once the array is unreferenced).
        self._pool_caches = pool_caches
        if pool_max_entries < 1:
            raise ValueError(
                f"pool_max_entries must be >= 1, got {pool_max_entries}"
            )
        self._pool_max = int(pool_max_entries)
        self._cache_pool: dict[int, decode.Cache] = {}
        # Fault sentinel: every program returns a per-row non-finite-logits
        # flag; with the guard on, ``generate`` fetches it (one tiny
        # host read per REQUEST, not per token), retries a poisoned
        # request ONCE on a fresh zeroed cache, then fails loudly
        # (lifecycle.RequestFailed) instead of returning garbage tokens.
        self._nan_guard = bool(nan_guard)
        # Monotonic request counters — the serial slice of the uniform
        # ``stats()`` schema (see BatchedDecodeEngine.stats). The
        # speculative counters are part of the uniform schema too: the
        # serial engine never drafts, so they stay 0 — consumers read
        # one key set whichever engine backs a replica.
        self.counters: dict[str, int] = {
            "requests": 0, "done": 0, "failed": 0, "nan_retries": 0,
            "drafted_tokens": 0, "accepted_tokens": 0, "spec_commits": 0,
        }

    def stats(self) -> dict[str, Any]:
        """Uniform engine-state snapshot — one schema across the serial,
        batched, and paged engines (the router's admission signal reads
        it without caring which engine backs a replica). The serial
        engine has no scheduler, so the occupancy fields are the fixed
        idle values and only ``counters`` carries information; paged-only
        fields are None on non-paged engines rather than absent, so
        consumers never need hasattr probes."""
        return {
            "engine": type(self).__name__,
            "role": "colocated",
            "device_ids": self.device_ids(),
            "queue_depth": 0,
            "queue_depth_by_tier": {name: 0 for name in PRIORITIES},
            "slots": None,
            "active_rows": 0,
            "free_slots": None,
            "pool_pages": None,
            "free_pages": None,
            "pages_in_use": None,
            "session_pinned_pages": None,
            "sessions": None,
            "prefix_hit_rate": None,
            "kv_quant": "none",
            "speculative_k": 0,
            "spec_accept_rate": _spec_accept_rate(self.counters),
            "counters": dict(self.counters),
        }

    def device_ids(self) -> list[int]:
        """Process-local device ids this engine's programs run on —
        the placement figure ``stats()`` reports so a fleet operator
        can SEE that replicas landed on disjoint hardware."""
        if self.mode == "plain":
            d = self.device if self.device is not None else jax.devices()[0]
            return [d.id]
        return [d.id for d in self._mesh.devices.flat]

    # -- cache pool --------------------------------------------------------

    def new_cache(self, batch: int) -> decode.Cache:
        """Freshly-zeroed cache placed for this engine's mode (the pool
        bypasses this after the first request per batch size)."""
        self._bump_cache_peak(batch)
        if self.mode == "tp":
            # Global [L, B, S, Hkv, D] array sharded over the head dim:
            # each shard holds its LOCAL kv heads, matching the local
            # n_kv view forward sees inside shard_map.
            full = decode.init_cache(self.cfg, batch, self.max_len)
            return jax.device_put(full, self._cache_sharding())
        cache = decode.init_cache(
            self.cfg, batch, self.max_len, n_kv=self._n_kv
        )
        if self.device is not None:
            # Committed inputs pin the jitted programs' outputs to the
            # same chip, so one device_put at allocation places the
            # whole request's compute.
            cache = jax.device_put(cache, self.device)
        return cache

    def _cache_bytes(self, batch: int) -> int:
        return batch * self.max_len * _kv_bytes_per_position(self.cfg)

    def _bump_cache_peak(self, taken_batch: int | None = None) -> None:
        live = sum(self._cache_bytes(b) for b in self._cache_pool)
        if taken_batch is not None:
            live += self._cache_bytes(taken_batch)
        if live > self._peak_cache_bytes:
            self._peak_cache_bytes = live

    def cache_hbm_bytes(self) -> dict[str, int]:
        """Pooled KV-cache HBM: ``allocated`` = the buffers currently
        retained by the LRU pool, ``peak_in_use`` = the high-water mark
        of pooled + in-flight bytes — the serial engine's row of the
        figure every serving bench leg reports (the batched/paged
        engines' slots x max_len / pool numbers are the comparison)."""
        return {
            "allocated": sum(
                self._cache_bytes(b) for b in self._cache_pool
            ),
            "peak_in_use": self._peak_cache_bytes,
        }

    def _take_cache(self, batch: int) -> decode.Cache:
        pooled = self._cache_pool.pop(batch, None)
        if pooled is not None:
            self._bump_cache_peak(batch)
            return pooled
        return self.new_cache(batch)

    def _return_cache(self, batch: int, cache: decode.Cache) -> None:
        if not self._pool_caches:
            return
        # Most-recently-used at the end (dict preserves insertion order);
        # evict from the front once the pool exceeds its LRU bound.
        self._cache_pool.pop(batch, None)
        self._cache_pool[batch] = cache
        while len(self._cache_pool) > self._pool_max:
            self._cache_pool.pop(next(iter(self._cache_pool)))

    def _cache_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._cache_spec()
        return jax.tree.map(
            lambda s: NamedSharding(self._mesh, s), spec,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _cache_spec(self):
        from jax.sharding import PartitionSpec as P

        s = (
            P(None, None, None, "tensor", None)
            if self.mode == "tp"
            else P()
        )
        return {"k": s, "v": s}

    # -- program construction ---------------------------------------------

    def _forward(self, params, ids, cache, pos):
        kwargs = {}
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        elif self.mode == "zero3":
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            kwargs["block_transform"] = lambda bp: jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(a, replicated),
                bp,
            )
            kwargs["prefetch_buffers"] = self._prefetch_buffers
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self, sampled: bool):
        """The three raw program bodies for one greedy/sampled variant.
        Sampling scalars are always in the signature (greedy programs
        trace-and-drop them) so every program keys the same way. Every
        body returns a traced NaN/Inf sentinel next to its tokens
        (``decode.nonfinite_rows`` over the sampled-position logits):
        elementwise + one reduction, no collectives — the registry
        budgets for these programs are unchanged by it."""

        def prefill(params, prompt, prompt_len, cache,
                    temperature, top_k, top_p, key):
            logits, cache = self._forward(params, prompt, cache, 0)
            last = jax.lax.dynamic_slice_in_dim(
                logits, prompt_len - 1, 1, axis=1
            )[:, 0]
            tok = decode.sample_token(
                last, sampled, temperature, key, top_k, top_p
            )
            return tok, decode.nonfinite_rows(last), cache

        def decode_run(params, tok, cache, pos, n_steps,
                       temperature, top_k, top_p, key):
            out = jnp.zeros((tok.shape[0], self.max_len), jnp.int32)
            bad = jnp.zeros((tok.shape[0],), jnp.bool_)

            def step(i, carry):
                out, bad, cache, tok = carry
                logits, cache = self._forward(
                    params, tok[:, None], cache, pos + i
                )
                last = logits[:, -1]
                nxt = decode.sample_token(
                    last, sampled, temperature,
                    jax.random.fold_in(key, i), top_k, top_p,
                )
                bad = bad | decode.nonfinite_rows(last)
                return out.at[:, i].set(nxt), bad, cache, nxt

            out, bad, cache, _ = jax.lax.fori_loop(
                0, n_steps, step, (out, bad, cache, tok)
            )
            return out, bad, cache

        def decode_step(params, tok, cache, pos,
                        temperature, top_k, top_p, key):
            logits, cache = self._forward(params, tok[:, None], cache, pos)
            last = logits[:, -1]
            tok = decode.sample_token(
                last, sampled, temperature, key, top_k, top_p
            )
            return tok, decode.nonfinite_rows(last), cache

        return {
            "prefill": prefill,
            "decode_run": decode_run,
            "decode_step": decode_step,
        }

    # The cache's positional index in each program signature — the
    # donate_argnums every mode passes and the donation audit verifies.
    CACHE_ARGNUM = {"prefill": 3, "decode_run": 2, "decode_step": 2}

    def program(self, kind: str, sampled: bool):
        """The jitted program for (kind, greedy/sampled), built lazily.
        Public so the audit registry (analysis/registry.py) and tests can
        lower/compile the exact programs the engine dispatches."""
        if kind not in _PROGRAM_KINDS:
            raise KeyError(f"unknown program kind {kind!r}")
        prog = self._programs.get((kind, sampled))
        if prog is not None:
            return prog
        body = self._bodies(sampled)[kind]
        donate = (self.CACHE_ARGNUM[kind],)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        elif self.mode == "tp":
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = self._cache_spec()
            # Everything but the params and the head-sharded cache is
            # replicated; signatures per _bodies.
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), cache_spec, P(), P(), P(), P()
                ),
                "decode_run": (
                    self._p_specs, P(), cache_spec,
                    P(), P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(), P(), P(), P()
                ),
            }[kind]
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=(P(), P(), cache_spec),
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        else:  # zero3
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            n_args = {"prefill": 8, "decode_run": 9, "decode_step": 8}[kind]
            in_sh = [replicated] * n_args
            in_sh[0] = self._param_shardings
            prog = jax.jit(
                body,
                in_shardings=tuple(in_sh),
                out_shardings=(replicated, replicated, replicated),
                donate_argnums=donate,
            )
        self._programs[(kind, sampled)] = prog
        return prog

    def _place_params(self, params):
        if self.weight_quant != "none":
            # Quantize ONCE per params tree (identity memo — "weights
            # quantized at engine build", with params staying call
            # arguments), then place the int8+scale tree.
            if self._prepared is None or self._prepared[0] is not params:
                q = quantize_decode_params(params)
                if self.mode != "plain":
                    q = jax.device_put(q, self._param_shardings)
                elif self.device is not None:
                    q = jax.device_put(q, self.device)
                self._prepared = (params, q)
            return self._prepared[1]
        if self.mode == "plain":
            if self.device is None:
                return params
            # Pin once per params tree (identity memo): committed params
            # + committed cache put every program output on self.device.
            if self._prepared is None or self._prepared[0] is not params:
                self._prepared = (
                    params, jax.device_put(params, self.device)
                )
            return self._prepared[1]
        # No-op when already placed, so repeat calls pay nothing.
        return jax.device_put(params, self._param_shardings)

    # -- request API -------------------------------------------------------

    def _request_setup(self, prompt, max_new_tokens, temperature,
                       top_k, top_p):
        # Budget overflow (prompt + max_new > max_len) is rejected by
        # decode._check_sample_args at every entry before this runs.
        prompt = jnp.asarray(prompt)
        b, tp = prompt.shape
        bucket = self.buckets.bucket_for(tp)
        padded = (
            prompt
            if bucket == tp
            else jnp.pad(prompt, ((0, 0), (0, bucket - tp)))
        )
        t, k, p = decode.sampling_scalars(
            temperature, top_k, top_p, self.cfg.vocab_size
        )
        return prompt, padded, b, tp, t, k, p

    def generate(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> jax.Array:
        """Serve one request: returns [B, Tp + max_new_tokens] — the same
        tokens the monolithic reference produces for this request. With
        ``nan_guard`` (default), non-finite logits anywhere in the
        request retry it ONCE on a fresh zeroed cache, then raise
        ``lifecycle.RequestFailed`` — garbage tokens never escape."""
        key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key, max_len=self.max_len
        )
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        self.counters["requests"] += 1
        for attempt in range(2 if self._nan_guard else 1):
            out, bad = self._generate_once(
                params, prompt, padded, b, tp, max_new_tokens, sampled,
                t, k, p, key, fresh_cache=attempt > 0,
            )
            if not self._nan_guard or not bool(np.asarray(bad).any()):
                self.counters["done"] += 1
                return out
            # Poisoned: drop the (pooled) buffer this request ran on and
            # retry once from a fresh zeroed allocation — the one failure
            # mode the masking discipline cannot absolve is a transient
            # corruption inside the request's own live rows.
            self._cache_pool.pop(b, None)
            self.counters["nan_retries"] += 1
            log_event(
                "nan_detected", engine="serial", batch=b,
                attempt=attempt, prompt_len=tp,
            )
        self.counters["failed"] += 1
        raise RequestFailed(
            "non-finite logits persisted after one fresh-cache retry "
            f"(batch={b}, prompt_len={tp}): the model/params produce "
            "NaN/Inf for this input — refusing to return garbage tokens"
        )

    def _generate_once(self, params, prompt, padded, b, tp,
                       max_new_tokens, sampled, t, k, p, key, *,
                       fresh_cache: bool):
        """One full prefill + decode_run attempt. Returns (tokens, bad)
        where ``bad`` is the device-side [B] non-finite sentinel OR-ed
        over every step of the request."""
        cache = self.new_cache(b) if fresh_cache else self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)

        # A failed dispatch DROPS the buffer instead of pooling it: once
        # a program was dispatched its donated input is consumed whether
        # or not the call succeeded, so returning it would poison the
        # pool with a deleted array; the next request simply re-allocates
        # (the cost a healthy pool avoids, paid only after a failure).
        try:
            tok, bad, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            pieces = [prompt.astype(jnp.int32), tok[:, None]]
            n = max_new_tokens - 1
            if n > 0:
                out, bad_run, cache = self.program("decode_run", sampled)(
                    params, tok, cache, plen, jnp.asarray(n, jnp.int32),
                    t, k, p, key,
                )
                pieces.append(out[:, :n])
                bad = jnp.logical_or(bad, bad_run)
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)
        return jnp.concatenate(pieces, axis=1), bad

    def stream(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ):
        """Generator of [B] int32 token arrays, one per ``decode_step``
        dispatch — the streaming form of ``generate`` (identical tokens:
        same programs modulo the fused loop, same key folding). The cache
        buffer returns to the pool when the generator finishes or is
        closed. With ``nan_guard``, a poisoned step raises
        ``lifecycle.RequestFailed`` immediately — a stream cannot retry
        transparently (tokens already escaped to the client), so the
        client resubmits; the per-step sentinel fetch costs nothing extra
        (streaming clients fetch every token anyway)."""
        key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key, max_len=self.max_len
        )
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        cache = self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)

        self.counters["requests"] += 1

        def _guard(bad):
            if self._nan_guard and bool(np.asarray(bad).any()):
                # Poisoned buffers never rejoin the pool.
                self.counters["failed"] += 1
                raise RequestFailed(
                    "non-finite logits mid-stream (batch="
                    f"{b}, prompt_len={tp}): aborting the stream — "
                    "resubmit via generate() for the fresh-cache retry"
                )

        # Same drop-on-dispatch-failure rule as generate(); an early
        # generator close (GeneratorExit at a yield) is NOT a failed
        # dispatch — `cache` is the last returned buffer and goes back
        # to the pool.
        try:
            tok, bad, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            _guard(bad)
            yield tok
            step = self.program("decode_step", sampled)
            for i in range(max_new_tokens - 1):
                tok, bad, cache = step(
                    params, tok, cache, jnp.asarray(tp + i, jnp.int32),
                    t, k, p, jax.random.fold_in(key, i),
                )
                _guard(bad)
                yield tok
            self.counters["done"] += 1
        except GeneratorExit:
            raise
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)

    # -- introspection -----------------------------------------------------

    def compile_count(self) -> int:
        """Total compiled executables across the engine's programs (the
        number a mixed-length request stream is asserted against:
        n_buckets prefills + 1 decode program per greedy/sampled mode)."""
        return sum(p._cache_size() for p in self._programs.values())

    def example_args(self, kind: str, params, *, batch: int = 1,
                     prompt_len: int | None = None, sampled: bool = True):
        """Example argument tuple for (lowering/auditing) ``kind`` — the
        shapes ``generate`` dispatches with."""
        tp = prompt_len or min(
            self.buckets.buckets[0] if self.buckets.buckets else 4,
            self.max_len - 1,
        )
        bucket = self.buckets.bucket_for(tp)
        t, k, p = decode.sampling_scalars(
            0.8 if sampled else 0.0, None, None, self.cfg.vocab_size
        )
        cache = self.new_cache(batch)
        key = jax.random.key(0)
        plen = jnp.asarray(tp, jnp.int32)
        prompt = jnp.zeros((batch, bucket), jnp.int32)
        tok = jnp.zeros((batch,), jnp.int32)
        if kind == "prefill":
            return (params, prompt, plen, cache, t, k, p, key)
        if kind == "decode_run":
            return (
                params, tok, cache, plen, jnp.asarray(2, jnp.int32),
                t, k, p, key,
            )
        if kind == "decode_step":
            return (params, tok, cache, plen, t, k, p, key)
        raise KeyError(f"unknown program kind {kind!r}")

    def verify_donation(self, params, *, batch: int = 1,
                        sampled: bool = True) -> dict[str, dict]:
        """Prove the KV cache actually aliases in/out of every engine
        program: lower + compile each (without running) and check the
        compiled module's input_output_alias map covers every cache leaf.
        Raises RuntimeError naming the program otherwise — a silently
        rejected donation would double-buffer the cache on every step.
        Returns {kind: alias stats} for reporting."""
        from pytorch_distributed_tpu.analysis.audit import check_donation

        stats_all: dict[str, dict] = {}
        for kind in _PROGRAM_KINDS:
            args = self.example_args(
                kind, params, batch=batch, sampled=sampled
            )
            compiled = self.program(kind, sampled).lower(*args).compile()
            findings, stats = check_donation(
                compiled.as_text(), args, (self.CACHE_ARGNUM[kind],),
                strict=True,
            )
            stats_all[kind] = stats
            if findings:
                raise RuntimeError(
                    f"engine program {kind!r} ({self.mode}): donated KV "
                    "cache does not fully alias in the compiled "
                    f"executable — {findings[0].message}"
                )
        return stats_all


@dataclasses.dataclass
class _Pending:
    """A queued request (host-side): everything the prefill dispatch
    needs, encoded once at submit time. The same record doubles as a
    RESUME entry after a fault (NaN quarantine, dispatch failure, engine
    replay): ``gen`` then holds the clean tokens generated before the
    fault, and admission prefills the whole prompt+gen prefix — with
    ``prefill_keydata`` pre-folded on the host to the prefix's position
    in the per-request fold schedule, so the continuation's draws are
    bit-identical to an undisturbed run."""

    rid: int
    prompt: np.ndarray  # [Tp] int32
    bucket: int
    max_new: int  # TOTAL new-token budget (not remaining)
    eos_id: int | None
    greedy: bool
    t: float
    k: int
    p: float
    keydata: np.ndarray  # base key-impl uint32 words (decode folds these)
    prefill_keydata: np.ndarray  # key for the admission prefill's draw
    deadline: float | None = None  # engine-clock absolute deadline
    gen: list = dataclasses.field(default_factory=list)  # resume prefix
    retries: int = 0  # fault-resume count (dispatch failures)
    nan_retried: bool = False  # quarantine: one retry, then FAILED
    # Workload-scenario fields (serving/scheduler.py / session.py /
    # adapters.py): the SLO tier rank, the session a turn belongs to,
    # how many tokens of its prompt are a resubmitted transcript (the
    # session hit-rate denominator), and the row's tenant adapter slot.
    tier: int = TIER_RANK[STANDARD]
    session: int | None = None
    resub_len: int = 0
    tenant_slot: int = 0


@dataclasses.dataclass
class _Slot:
    """One occupied row of the slot batch (host-side scheduler state)."""

    rid: int
    prompt: np.ndarray
    max_new: int
    eos_id: int | None
    pos: int  # tokens in the row's cache = next KV write offset
    fold: int  # fold_in counter for the row's NEXT sampled draw
    generated: list
    greedy: bool
    t: float
    k: int
    p: float
    keydata: np.ndarray
    deadline: float | None = None
    retries: int = 0
    nan_retried: bool = False
    tier: int = TIER_RANK[STANDARD]
    session: int | None = None
    resub_len: int = 0
    tenant_slot: int = 0


class BatchedDecodeEngine:
    """Continuous batching: slot-scheduled multi-request decode.

    ``DecodeEngine`` serves one request shape at a time — under real
    traffic the batch dimension idles while requests queue. This engine
    keeps ONE long-lived ``(slots, max_len)`` KV cache whose rows are
    independent requests at unrelated depths: a host-side scheduler
    admits queued prompts into free rows (bucketed per-row prefill, or
    one batched prefill when several arrivals share a bucket), a single
    compiled ``decode_step`` advances ALL rows one token per dispatch,
    and finished rows retire without touching their neighbours. Every
    per-row quantity — position, fold counter, greedy flag,
    temperature/top_k/top_p, PRNG key — is a TRACED [slots] operand, so
    admissions, retirements, sampling-config changes, and any
    active-row pattern reuse the same executables: steady-state serving
    is zero-recompile BY CONSTRUCTION (shapes never change — the pjit
    fixed-shape compilation discipline), and the collective count of the
    TP program is invariant to how many rows are active (pinned in the
    audit registry).

    Soundness of row reuse is the PR-4 dirty-cache discipline at ROW
    granularity: a retired row's K/V stays in place; the next admission
    prefills over it, and per-row masking (``decode._cached_attention``
    with a [B] pos vector) guarantees no row ever reads cache positions
    past its own write point — including the GQA head-repeat edge
    (tests/test_serving_batched.py).

    The decode program is deliberately OBLIVIOUS to which rows are
    active: free rows compute garbage that the host discards. Gating
    them with a mask would save nothing (the shapes are fixed) and would
    make program behaviour depend on activity — exactly what the
    zero-recompile and collective-count contracts forbid. ``active`` is
    therefore host-side scheduler state, not a program operand.

    Modes: plain and tp (head-sharded global cache — 1/tp of the cache
    HBM per chip). ZeRO-3 slot batching and TP x ZeRO-3 stay rejected
    with explicit diagnostics (``_select_mode``). MoE configs are
    rejected: expert capacity couples rows through the dispatch (a busy
    neighbour could evict a row's tokens), breaking the per-row
    independence this engine is built on.

    Unlike the serial engine there is no greedy/sampled program split:
    one batch serves both kinds of row, so greedy is a traced per-row
    flag and the full-vocab sort always runs (see
    ``decode.sample_token_rows``). Program count: ONE decode_step shape
    + (buckets x prefill group sizes) prefill shapes — compile_count()
    is asserted flat across admit/retire churn in tests.

    **Batched speculative decoding** (``speculative_k=K`` > 0): decode
    is bandwidth-bound — every tick streams the whole model to emit ONE
    token per row — so each tick instead drafts up to K tokens per
    GREEDY row host-side (prompt-lookup n-gram match over the row's
    tokens-so-far, ``models/speculative.prompt_lookup_draft``; or the
    engine's ``draft_hook``) and verifies ALL rows' drafts in ONE
    [slots, K+1] ``decode_spec_step`` forward. Accept lengths are
    per-row TRACED outputs (``decode.speculative_accept``), so rows
    accepting 0..K tokens share one compiled program — the decode tick
    count drops by the mean accepted length while every contract above
    (zero steady compiles, strict donation, rows-invariant collectives)
    holds verbatim. Greedy speculative output is TOKEN-EQUAL to the
    non-speculative engine by construction: the verification forward is
    the ground truth, drafts only change speed. Sampled rows ride the
    same program with zero drafts (their lane-0 draw bit-matches the
    plain step; exact sampled speculation needs rejection-sampling
    corrections — out of scope). When drafting LOSES — low-repetition
    streams pay the (K+1)-wide verify for ~0 accepts — see
    benchmarks/PERF_NOTES.md.

    Not thread-safe (single dispatcher per engine); requests are
    single-sequence (one row each — batch your own beams as separate
    requests).

    **Request lifecycle + fault model** (docs/ROBUSTNESS.md): every
    request reaches exactly one terminal ``RequestResult`` state —
    DONE / FAILED / ABORTED / EXPIRED — delivered via ``pop_result``.
    Per-request deadlines (``submit(timeout_s=...)``) expire queued AND
    mid-decode requests with their clean partial output; ``abort(rid)``
    retires a slot row mid-decode as pure host bookkeeping (traced
    shapes untouched — no recompile, neighbours unperturbed); the
    admission queue is bounded (``queue_limit`` + reject-loudly or
    block-with-timeout backpressure). Both compiled programs return a
    traced NaN/Inf logit sentinel next to their tokens; a poisoned row
    is QUARANTINED (freed, requeued, its prefix re-prefilled over a
    fresh row — neighbours keep decoding untouched), retried once, then
    FAILED. A failed/dropped dispatch consumed the donated cache, so
    EVERY in-flight row converts to a resume entry (tokens-so-far +
    pre-folded PRNG schedule) and is re-prefilled on the next tick —
    bounded by per-request ``request_retries`` and engine-level
    consecutive ``dispatch_retries`` with exponential backoff.
    ``snapshot()`` captures that same host state at any tick boundary;
    ``restore()`` on a rebuilt engine after device loss re-prefills
    every in-flight request and continues token-identically. The
    deterministic fault-injection harness (serving/chaos.py) drives all
    of these paths in tests and scripts/soak.py.
    """

    # The donated cache's positional index in each program signature.
    CACHE_ARGNUM = {"prefill": 4, "decode_step": 2, "decode_spec_step": 2}

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        slots: int,
        max_len: int,
        buckets: BucketSpec | None = None,
        mesh_cfg: MeshConfig | None = None,
        prefill_groups: tuple[int, ...] | None = None,
        queue_limit: int | None = None,
        backpressure: str = "reject",
        request_retries: int = 3,
        dispatch_retries: int | None = 2,
        retry_backoff_s: float = 0.05,
        clock=None,
        sleep=None,
        weight_quant: str = "none",
        adapters=None,
        speculative_k: int = 0,
        spec_ngram: int = 2,
        draft_hook=None,
        device: int | None = None,
    ) -> None:
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_len > cfg.n_ctx:
            raise ValueError(f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}")
        if cfg.n_experts:
            raise NotImplementedError(
                "BatchedDecodeEngine does not serve MoE configs: expert "
                "capacity couples batch rows through the dispatch, so a "
                "row's output would depend on its neighbours — use the "
                "serial DecodeEngine for MoE decode"
            )
        self.cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.buckets = buckets or BucketSpec()
        if self.buckets.buckets and self.buckets.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket {self.buckets.buckets[-1]} exceeds "
                f"max_len {max_len}"
            )
        if prefill_groups is None:
            # Powers of two up to the slot count: a burst of n same-bucket
            # arrivals pads to the next group size, so prefill compiles
            # O(buckets x log slots) shapes, not O(buckets x slots).
            groups = []
            g = 1
            while g < self.slots:
                groups.append(g)
                g *= 2
            groups.append(self.slots)
            prefill_groups = tuple(groups)
        pg = tuple(sorted(set(int(g) for g in prefill_groups)))
        if not pg or pg[0] < 1 or pg[-1] < self.slots:
            raise ValueError(
                f"prefill_groups must be positive and cover the slot "
                f"count {self.slots}, got {prefill_groups}"
            )
        self._groups = pg
        self.mode, self.mesh_cfg, self._n_kv, _ = _select_mode(
            cfg, mesh_cfg, entry="BatchedDecodeEngine", allow_zero3=False
        )
        self.device = _resolve_device(device)
        if self.device is not None and self.mode != "plain":
            raise ValueError(
                "device= pins the single-device (plain) engine to one "
                "chip; meshed modes place via MeshConfig.device_ids"
            )
        # Disaggregation role: the dense engine always runs colocated
        # (KV handoff ships PAGES — PagedBatchedDecodeEngine overrides
        # this with its role= knob); the attribute exists here so the
        # uniform stats() schema carries one key set for every engine.
        self.role = "colocated"
        # Per-row speculative decoding (batched prompt-lookup — ROADMAP
        # direction 3): with speculative_k=K > 0 every decode tick
        # drafts up to K tokens per GREEDY row host-side (zero model
        # cost; ``draft_hook(tokens_so_far, k) -> drafts`` overrides the
        # n-gram lookup, e.g. for a small draft model later) and ONE
        # batched ``decode_spec_step`` forward verifies all rows'
        # drafts with per-row TRACED accept lengths — rows accepting
        # 0..K tokens share one compiled program, so the zero-steady-
        # compile / strict-donation / rows-invariant-collective
        # contracts survive unchanged. K=0 keeps the exact pre-spec
        # programs (decode_spec_step is never built). Sampled rows ride
        # the same program with zero drafts: distribution-exact sampled
        # speculation needs rejection-sampling corrections, which stay
        # out of scope (models/speculative.py).
        if speculative_k < 0:
            raise ValueError(
                f"speculative_k must be >= 0, got {speculative_k} "
                "(0 disables speculation)"
            )
        if speculative_k >= max_len:
            raise ValueError(
                f"speculative_k ({speculative_k}) must be < max_len "
                f"({max_len}): the verify window is k+1 tokens wide and "
                "has to fit a row's cache extent"
            )
        if spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {spec_ngram}")
        if draft_hook is not None and not callable(draft_hook):
            raise ValueError(
                "draft_hook must be callable: (tokens_so_far [n] int32, "
                "k) -> up to k draft tokens"
            )
        self.speculative_k = int(speculative_k)
        self.spec_ngram = int(spec_ngram)
        self._draft_hook = draft_hook
        self.weight_quant = _check_quant_arg("weight_quant", weight_quant)
        # Multi-tenant LoRA (serving/adapters.py): when a registry is
        # attached, every dispatch carries TWO extra traced operands —
        # the stacked adapter tree and a [B] tenant-slot vector — so the
        # program SIGNATURES differ from the adapter-less engine (built
        # once, at construction; registration later changes values,
        # never shapes, hence never programs). No registry = the exact
        # pre-LoRA programs, so the existing audit pins are untouched.
        if adapters is not None and adapters.cfg != cfg:
            raise ValueError(
                "adapters= was built for a different ModelConfig than "
                "this engine serves — one registry per architecture "
                "(build it once and share it across replicas)"
            )
        self.adapters = adapters
        if self.mode == "tp":
            (
                self._mesh, self._p_specs, self._param_shardings
            ) = decode._mesh_param_shardings(cfg, self.mesh_cfg)
            if self.weight_quant != "none":
                self._p_specs, self._param_shardings = (
                    _quantized_mesh_specs(cfg, self._mesh, self._p_specs)
                )
        self._programs: dict[str, Any] = {}
        # ONE cache for the engine's whole life, donated through every
        # dispatch — HBM is bounded at exactly one (slots, max_len) cache
        # by construction (no pool to bound). None = not yet allocated,
        # or dropped after a failed dispatch (the donated input is
        # consumed either way; the next dispatch re-allocates zeros and
        # per-row masking makes the lost garbage irrelevant — but the
        # in-flight rows lost their K/V, so a failure aborts them).
        self._cache: decode.Cache | None = None
        self._key_words = np.asarray(
            jax.random.key_data(jax.random.key(0))
        ).shape[-1]
        self._queue: collections.deque[_Pending] = collections.deque()
        self._slots: list[_Slot | None] = [None] * self.slots
        self._next_rid = 0
        # (source tree, placed tree): _place_params runs once per
        # scheduler tick — one jax.device_put tree traversal per TOKEN
        # without this identity memo (the serial engine pays it once per
        # request; holding the source keeps its id from being recycled).
        self._placed: tuple[Any, Any] | None = None
        self.results: dict[int, RequestResult] = {}

        # -- robustness layer (see class docstring) ---------------------
        if backpressure not in ("reject", "block"):
            raise ValueError(
                f"backpressure must be 'reject' or 'block', got "
                f"{backpressure!r}"
            )
        if queue_limit is not None and queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.queue_limit = queue_limit
        self.backpressure = backpressure
        self.request_retries = int(request_retries)
        self.dispatch_retries = dispatch_retries
        self.retry_backoff_s = float(retry_backoff_s)
        # Injectable time sources: the chaos harness (serving/chaos.py)
        # substitutes a VirtualClock so deadlines, backoff, and slow-tick
        # faults are DETERMINISTIC; production uses the monotonic clock.
        self._clock = clock or time.monotonic
        self._sleep = sleep or time.sleep
        self._injector = None  # serving/chaos.FaultInjector (or None)
        self._ticks = 0
        self._fail_streak = 0  # consecutive failed dispatches
        # Prefill shapes the engine may dispatch: the user buckets plus
        # max_len — fault-resume prefixes (prompt + tokens-so-far) can
        # exceed the largest PROMPT bucket, and the extra bucket keeps
        # them inside the warmed, finite compile set (fresh submissions
        # still obey the user BucketSpec contract unchanged).
        pb = tuple(self.buckets.buckets)
        if pb and pb[-1] < self.max_len:
            pb = pb + (self.max_len,)
        self._prefill_buckets = pb  # () = exact-length mode
        # Monotonic event counters (terminal states + fault/recovery
        # tallies). The point-in-time scheduler view lives in ``stats()``
        # — the router's admission signal — which embeds a copy of these.
        self.counters: dict[str, int] = {
            "done": 0, "failed": 0, "aborted": 0, "expired": 0,
            "nan_quarantines": 0, "dispatch_failures": 0, "resumes": 0,
            "cache_allocs": 0,
            # Speculation (monotonic; 0 forever when speculative_k=0):
            # drafted = lanes offered to the verifier, accepted = extra
            # tokens committed beyond the one a plain tick yields,
            # spec_commits = row-ticks that went through the verify
            # path (the mean-accepted-length denominator).
            "drafted_tokens": 0, "accepted_tokens": 0, "spec_commits": 0,
        }

    # -- cache -------------------------------------------------------------

    def _new_cache(self) -> decode.Cache:
        self.counters["cache_allocs"] += 1
        if self.mode == "tp":
            full = decode.init_cache(self.cfg, self.slots, self.max_len)
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = P(None, None, None, "tensor", None)
            sharding = jax.tree.map(
                lambda s: NamedSharding(self._mesh, s),
                {"k": spec, "v": spec},
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.device_put(full, sharding)
        cache = decode.init_cache(
            self.cfg, self.slots, self.max_len, n_kv=self._n_kv
        )
        if self.device is not None:
            # Committed inputs pin every jitted program's outputs to
            # the same chip — one device_put per cache alloc places the
            # engine's whole steady-state compute.
            cache = jax.device_put(cache, self.device)
        return cache

    def _take_cache(self) -> decode.Cache:
        cache, self._cache = self._cache, None
        return cache if cache is not None else self._new_cache()

    # -- programs ----------------------------------------------------------

    def _forward(self, params, ids, cache, pos, lora=None):
        kwargs = {}
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        if lora:
            kwargs["lora"] = lora
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self):
        """The two raw program bodies. All sampling state is per-row and
        traced; ``rows``/``pos``/``folds`` are traced index vectors, so
        one compiled shape covers every admission/retirement pattern.
        Both return a [B] traced non-finite-logits sentinel
        (``decode.nonfinite_rows`` over the sampled position) — the
        scheduler quarantines flagged rows; elementwise + one reduction,
        so the pinned collective budgets (registry:
        decode_batched_step_tp all-reduce=2) are untouched by it.

        With an adapter registry attached, both bodies take two trailing
        operands — the stacked LoRA tree and the [B] tenant-slot vector
        (``*lora``) — applied inside ``decode.forward`` as per-row
        deltas; without one the signatures are byte-identical to the
        pre-LoRA engine."""

        def prefill(params, prompts, plens, rows, cache,
                    greedy, t, k, p, keydata, *lora):
            # Gather the target rows' (dirty) segments, run the normal
            # prefill forward over them at pos 0, scatter back. Padded
            # group entries duplicate row index AND data, so the
            # overlapping scatter writes are identical (deterministic).
            seg = {kk: vv[:, rows] for kk, vv in cache.items()}
            logits, seg = self._forward(params, prompts, seg, 0, lora)
            last = jnp.take_along_axis(
                logits, (plens - 1)[:, None, None], axis=1
            )[:, 0]
            keys = jax.random.wrap_key_data(keydata)
            tok = decode.sample_token_rows(last, greedy, t, keys, k, p)
            cache = {
                kk: cache[kk].at[:, rows].set(seg[kk]) for kk in cache
            }
            return tok, decode.nonfinite_rows(last), cache

        def decode_step(params, toks, cache, pos, folds,
                        greedy, t, k, p, keydata, *lora):
            logits, cache = self._forward(
                params, toks[:, None], cache, pos, lora
            )
            last = logits[:, -1]
            keys = jax.vmap(jax.random.fold_in)(
                jax.random.wrap_key_data(keydata), folds
            )
            tok = decode.sample_token_rows(last, greedy, t, keys, k, p)
            return tok, decode.nonfinite_rows(last), cache

        def decode_spec_step(params, toks, cache, pos, folds,
                             greedy, t, k, p, keydata, n_draft, *lora):
            # ``toks`` [B, K+1]: lane 0 = each row's last committed
            # token, lanes 1..K = host drafts (lane-padded; n_draft [B]
            # marks the valid count). ONE forward verifies every row's
            # window; per-row accept lengths are traced, so 0..K
            # accepts share this executable. Lane 0's sampled draw uses
            # the row's ordinary fold schedule — a zero-draft row (and
            # every sampled row) commits exactly the plain decode_step
            # token.
            return self._spec_verify(
                self._forward(params, toks, cache, pos, lora),
                toks, folds, greedy, t, k, p, keydata, n_draft,
            )

        return {
            "prefill": prefill,
            "decode_step": decode_step,
            "decode_spec_step": decode_spec_step,
        }

    @staticmethod
    def _spec_verify(forward_out, toks, folds, greedy, t, k, p,
                     keydata, n_draft):
        """Shared verification tail of both engines' spec bodies (the
        dense/paged programs differ only in how the forward is wired):
        sample lane 0 with the row's key/fold (bit-matching the plain
        step), take the model's own greedy chain over the window, and
        compute the traced accept lengths. Returns
        (out [B, K+1], n_acc [B], bad [B], cache) — the host commits
        ``out[b, :n_acc[b]+1]``, clipped by EOS/budget."""
        logits, cache = forward_out  # [B, K+1, V]
        keys = jax.vmap(jax.random.fold_in)(
            jax.random.wrap_key_data(keydata), folds
        )
        tok0 = decode.sample_token_rows(
            logits[:, 0], greedy, t, keys, k, p
        )
        ver = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
        n_acc = decode.speculative_accept(
            toks[:, 1:], ver[:, :-1], n_draft
        )
        out = jnp.concatenate([tok0[:, None], ver[:, 1:]], axis=1)
        # NaN anywhere in the window flags the row: any lane's logits
        # could decide a committed token (one reduction, no collectives
        # — the pinned budgets are untouched, like every sentinel).
        return out, n_acc, decode.nonfinite_rows(logits), cache

    def _lora_dispatch_args(self, tenant_slots) -> tuple:
        """The two trailing LoRA operands for one dispatch — the
        (version-memoized) stacked adapter tree and the per-row tenant
        slots — or () when no registry is attached (the signatures then
        stay the pre-LoRA ones). Free/garbage rows ride slot 0, the
        exact-zero adapter."""
        if self.adapters is None:
            return ()
        return (
            self.adapters.device_tree(),
            jnp.asarray(tenant_slots, jnp.int32),
        )

    def _lora_in_specs(self) -> tuple:
        """shard_map in_specs for the two LoRA operands under TP (empty
        without a registry): the factor tree shards per
        ``AdapterRegistry.partition_specs`` — column-parallel B factors
        with their base weight's output axis, row-parallel A factors on
        the contracting dim — and the tenant-slot vector replicates."""
        if self.adapters is None:
            return ()
        from jax.sharding import PartitionSpec as P

        return (self.adapters.partition_specs(), P())

    def _check_program_kind(self, kind: str) -> None:
        if kind not in _BATCHED_PROGRAM_KINDS:
            raise KeyError(f"unknown batched program kind {kind!r}")
        if kind == "decode_spec_step" and not self.speculative_k:
            raise KeyError(
                "decode_spec_step exists only on engines built with "
                "speculative_k > 0 (this engine decodes one token per "
                "row per tick)"
            )
        if kind == "decode_step" and self.speculative_k:
            # Symmetric gate: a spec engine routes EVERY decode tick
            # through decode_spec_step, so silently building the plain
            # step here would cache an executable the engine never
            # dispatches — and inflate compile_count() under the pinned
            # zero-steady-compile assertions.
            raise KeyError(
                "this engine was built with speculative_k="
                f"{self.speculative_k}: every decode tick dispatches "
                "decode_spec_step — request that kind instead"
            )

    def _program_kinds(self) -> tuple[str, ...]:
        """The program kinds THIS engine actually dispatches: a spec
        engine's every decode tick goes through decode_spec_step (rows
        without drafts ride zero-draft lanes), so the plain decode_step
        is never built there — and vice versa."""
        return (
            "prefill",
            "decode_spec_step" if self.speculative_k else "decode_step",
        )

    def program(self, kind: str):
        """The jitted program for ``kind`` — public for the audit
        registry (analysis/registry.py) and tests, like
        ``DecodeEngine.program``."""
        self._check_program_kind(kind)
        prog = self._programs.get(kind)
        if prog is not None:
            return prog
        body = self._bodies()[kind]
        donate = (self.CACHE_ARGNUM[kind],)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        else:  # tp
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = {
                "k": P(None, None, None, "tensor", None),
                "v": P(None, None, None, "tensor", None),
            }
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), P(), cache_spec,
                    P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(),
                    P(), P(), P(), P(), P(),
                ),
                # decode_step + the [B] n_draft operand; outputs grow
                # the replicated [B] accept lengths.
                "decode_spec_step": (
                    self._p_specs, P(), cache_spec, P(), P(),
                    P(), P(), P(), P(), P(), P(),
                ),
            }[kind] + self._lora_in_specs()
            out_specs = (
                (P(), P(), P(), cache_spec)
                if kind == "decode_spec_step"
                else (P(), P(), cache_spec)
            )
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=out_specs,
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        self._programs[kind] = prog
        return prog

    def _place_params(self, params):
        if (self.mode == "plain" and self.weight_quant == "none"
                and self.device is None):
            return params
        if self._placed is None or self._placed[0] is not params:
            prepared = (
                quantize_decode_params(params)
                if self.weight_quant != "none"
                else params
            )
            if self.mode != "plain":
                prepared = jax.device_put(prepared, self._param_shardings)
            elif self.device is not None:
                prepared = jax.device_put(prepared, self.device)
            self._placed = (params, prepared)
        return self._placed[1]

    def device_ids(self) -> list[int]:
        """Process-local device ids this engine's programs run on —
        ``stats()``'s placement figure (see DecodeEngine.device_ids)."""
        if self.mode == "plain":
            d = self.device if self.device is not None else jax.devices()[0]
            return [d.id]
        return [d.id for d in self._mesh.devices.flat]

    # -- request API -------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
        timeout_s: float | None = None,
        params=None,
        block_timeout_s: float | None = None,
        priority: str = STANDARD,
        session: int | None = None,
        tenant=None,
    ) -> int:
        """Queue one single-sequence request ([Tp] or [1, Tp] int ids);
        returns its request id. The request is admitted into a free slot
        by a later ``step``; its terminal ``RequestResult`` lands in
        ``self.results[rid]`` — collect it with ``pop_result(rid)``
        (long-lived engines leak host memory otherwise).

        ``timeout_s``: per-request deadline on the ENGINE clock; a
        request still queued or mid-decode when it passes retires
        EXPIRED with its clean partial output. Backpressure: with no
        ``queue_limit`` the queue itself is the backpressure (submissions
        beyond the slot count wait their FIFO turn); with one, the
        ``reject`` policy raises ``AdmissionQueueFull`` loudly, and the
        ``block`` policy drives the scheduler (``params`` required) until
        space frees or ``block_timeout_s`` passes, then raises.

        Workload scenarios (all host-side — traced programs never see
        them): ``priority`` is the SLO tier (serving/scheduler.py —
        'interactive' admits ahead of the queue, deadline-first within
        the tier; 'standard' is exactly the pre-tier FIFO). On the
        DENSE engine tiers only reorder admission; the paged engine
        additionally lets interactive preempt lower tiers, gates
        'batch' admission on pool headroom, and preempts batch first.
        ``session`` is a live session id from the
        paged engine's ``open_session`` — the prompt must resubmit the
        conversation-so-far and pays ~one chunk of prefill via the
        pinned prefix cache. ``tenant`` picks a registered LoRA adapter
        (engine built with ``adapters=``); None rides the shared zero
        adapter bit-equal to the adapter-less engine."""
        prompt = np.asarray(prompt)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1:
            raise ValueError(
                f"BatchedDecodeEngine serves one sequence per request "
                f"(one slot row); got prompt shape {prompt.shape}"
            )
        tp = prompt.shape[0]
        decode._check_sample_args(
            prompt, max_new_tokens, temperature, key, max_len=self.max_len
        )
        if timeout_s is not None and timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        tier = check_priority(priority)
        tenant_slot = 0
        if tenant is not None:
            if self.adapters is None:
                raise ValueError(
                    f"tenant={tenant!r} needs an engine built with "
                    "adapters=AdapterRegistry(...) — this engine has no "
                    "adapter registry attached"
                )
            tenant_slot = self.adapters.slot(tenant)
        prompt = prompt.astype(np.int32)
        # Validates BEFORE the rid is assigned (a rejected turn must not
        # burn an id) but marks the turn in flight only after.
        resub_len = self._session_checkin(session, prompt)
        self._admission_backpressure(params, block_timeout_s)
        rid = self._next_rid
        self._next_rid += 1
        bucket = self.buckets.bucket_for(tp)
        t, k, p = decode.sampling_scalars(
            temperature, top_k, top_p, self.cfg.vocab_size
        )
        keydata = (
            np.asarray(jax.random.key_data(key))
            if key is not None
            else np.zeros((self._key_words,), np.uint32)
        )
        deadline = (
            None if timeout_s is None else self._clock() + timeout_s
        )
        self._queue.append(_Pending(
            rid=rid, prompt=prompt, bucket=bucket,
            max_new=int(max_new_tokens), eos_id=eos_id,
            greedy=not temperature > 0.0,
            t=float(t), k=int(k), p=float(p), keydata=keydata,
            prefill_keydata=keydata, deadline=deadline,
            tier=tier, session=session, resub_len=resub_len,
            tenant_slot=tenant_slot,
        ))
        self._session_begin(session, rid)
        log_event(
            "submit", rid=rid, t=round(self._clock(), 6), prompt_len=tp,
            max_new=int(max_new_tokens),
            deadline=None if deadline is None else round(deadline, 6),
            priority=priority if tier != TIER_RANK[STANDARD] else None,
            session=session,
            tenant=str(tenant) if tenant is not None else None,
        )
        return rid

    def _session_checkin(self, session, prompt) -> int:
        """Hook: validate a session turn and return its resubmitted-
        transcript length. Sessions ride the paged engine's prefix cache
        — the dense engines reject them loudly."""
        if session is not None:
            raise ValueError(
                "multi-turn sessions need the chunk-chained prefix "
                "cache and page pinning — open them on a "
                "PagedBatchedDecodeEngine (serving/session.py), not "
                f"{type(self).__name__}"
            )
        return 0

    def _session_begin(self, session, rid) -> None:
        """Hook: mark a validated session turn in flight (paged only)."""

    def _admission_backpressure(self, params, block_timeout_s) -> None:
        if self.queue_limit is None or len(self._queue) < self.queue_limit:
            return
        if self.backpressure == "reject":
            raise AdmissionQueueFull(
                f"admission queue full: {len(self._queue)} queued >= "
                f"queue_limit {self.queue_limit} (policy 'reject') — "
                "shed load upstream or retry after draining"
            )
        # block: drive the scheduler until space frees or timeout.
        if params is None:
            raise ValueError(
                "backpressure policy 'block' drives the scheduler from "
                "submit and therefore needs params=... (or use the "
                "'reject' policy)"
            )
        deadline = (
            None
            if block_timeout_s is None
            else self._clock() + block_timeout_s
        )
        while len(self._queue) >= self.queue_limit:
            if deadline is not None and self._clock() >= deadline:
                raise AdmissionQueueFull(
                    f"admission queue still full ({len(self._queue)} >= "
                    f"queue_limit {self.queue_limit}) after blocking "
                    f"{block_timeout_s}s — the engine is not draining "
                    "fast enough for the offered load"
                )
            self.step(params)

    def has_work(self) -> bool:
        return bool(self._queue) or any(
            s is not None for s in self._slots
        )

    def queued_rids(self) -> list[int]:
        return [q.rid for q in self._queue]

    def active_rids(self) -> list[int]:
        return [s.rid for s in self._slots if s is not None]

    def abort(self, rid: int) -> bool:
        """Cancel one request mid-flight. Pure host bookkeeping: a
        queued entry is removed, an ACTIVE slot row is freed (its K/V
        stays in place, dirty — the traced shapes and the compiled
        programs are untouched, so an abort can never recompile and
        neighbours decode on unperturbed). The request retires ABORTED
        with its clean partial output. Returns True on transition, False
        if the request already reached a terminal state; unknown rids
        raise KeyError."""
        for q in self._queue:
            if q.rid == rid:
                self._queue.remove(q)
                self._finish_pending(q, ABORTED, "abort() while queued")
                return True
        for i, s in enumerate(self._slots):
            if s is not None and s.rid == rid:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._finish_slot(s, ABORTED, "abort() mid-decode")
                return True
        if rid in self.results:
            return False
        raise KeyError(
            f"unknown rid {rid}: never submitted, or already delivered "
            "via pop_result"
        )

    def step(self, params) -> list[int]:
        """One scheduler tick: expire overdue requests, admit queued
        requests into free slots (prefill), then advance every active
        row one token (one batched decode dispatch). Returns the rids
        that reached a terminal state this tick.

        A failed/dropped dispatch is RECOVERED here, not surfaced: every
        in-flight row converts to a resume entry (re-prefilled from its
        tokens-so-far on a later tick), bounded by per-request
        ``request_retries``; only when ``dispatch_retries`` CONSECUTIVE
        dispatches fail does step raise ``DispatchFailure`` — with the
        engine state still consistent (everything requeued)."""
        self._ticks += 1
        if self._injector is not None:
            self._injector.on_tick(self._ticks)
        params = self._place_params(params)
        finished: list[int] = []
        self._expire(finished)
        self._admit(params, finished)
        if any(s is not None for s in self._slots):
            self._decode_tick(params, finished)
        return finished

    def run(
        self, params, requests=None, *,
        max_ticks: int | None = None,
        timeout_s: float | None = None,
    ) -> dict[int, RequestResult]:
        """Submit ``requests`` (iterable of ``submit`` kwarg dicts), then
        drive ``step`` until idle. Returns {rid: RequestResult} for
        everything that reached a terminal state during the drive
        (including previously queued work).

        ``max_ticks`` / ``timeout_s`` (engine clock) bound the drive: a
        hung or permanently-faulting stream terminates with the partial
        results collected so far (remaining work stays queued/active in
        the engine) instead of looping forever."""
        before = set(self.results)
        for req in requests or ():
            self.submit(**req)
        deadline = (
            None if timeout_s is None else self._clock() + timeout_s
        )
        ticks = 0
        while self.has_work():
            if max_ticks is not None and ticks >= max_ticks:
                log_event(
                    "run_guard", reason="max_ticks", ticks=ticks,
                    queued=len(self._queue),
                    active=len(self.active_rids()),
                )
                break
            if deadline is not None and self._clock() >= deadline:
                log_event(
                    "run_guard", reason="timeout", ticks=ticks,
                    queued=len(self._queue),
                    active=len(self.active_rids()),
                )
                break
            self.step(params)
            ticks += 1
        return {
            rid: out for rid, out in self.results.items()
            if rid not in before
        }

    def pop_result(self, rid: int) -> RequestResult:
        """Deliver and RELEASE one request's terminal ``RequestResult``
        (state DONE/FAILED/ABORTED/EXPIRED + tokens + reason), dropping
        the engine's reference. A long-lived engine retains every
        retired request's result in ``results`` until delivered —
        serving loops must pop (or ``del``) what they consume, or host
        memory grows per request forever. KeyError for unknown or
        not-yet-terminal rids."""
        return self.results.pop(rid)

    def warmup(self, params) -> int:
        """Compile every (bucket x prefill-group) shape plus the decode
        program with dummy dispatches (idle engines only — warmup writes
        garbage rows), so a serving loop's steady state starts
        compile-free. Covers the fault-resume max_len bucket too, so
        recovery re-prefills never compile mid-incident. Returns
        compile_count()."""
        if self.has_work():
            raise RuntimeError("warmup requires an idle engine")
        if not self._prefill_buckets:
            raise ValueError(
                "warmup needs a finite BucketSpec (exact-length mode "
                "compiles per observed prompt length)"
            )
        params = self._place_params(params)
        for bucket in self._prefill_buckets:
            for g in self._groups:
                args = self.example_args(
                    "prefill", params, bucket=bucket, group=g,
                    cache=self._take_cache(),
                )
                _, _, cache = self.program("prefill")(*args)
                self._cache = cache
        self._rewarm_first_prefill(params)
        step_kind = self._program_kinds()[-1]
        args = self.example_args(
            step_kind, params, cache=self._take_cache()
        )
        *_, cache = self.program(step_kind)(*args)
        self._cache = cache
        return self.compile_count()

    def _rewarm_first_prefill(self, params) -> None:
        """Close a meshed-warmup hole: the warmup loop's FIRST dispatch
        keyed its executable on the freshly ``device_put`` cache's
        sharding, but every steady-state dispatch presents the
        donated-OUTPUT sharding instead — which can hash differently,
        so the first shape recompiled once mid-traffic (observed on TP;
        regression-pinned by the zero-steady-compile assertions in
        decode_bench --serving-spec and tests). Re-dispatching that one
        shape with the laundered cache keys the warm set exactly as
        serving will hit it."""
        if self.mode == "plain":
            return
        args = self.example_args(
            "prefill", params,
            bucket=(
                self._prefill_buckets[0] if self._prefill_buckets
                else None
            ),
            group=self._groups[0], cache=self._take_cache(),
        )
        _, _, cache = self.program("prefill")(*args)
        self._cache = cache

    # -- fault injection / crash recovery ------------------------------------

    def set_fault_injector(self, injector) -> None:
        """Install a serving/chaos.FaultInjector (or None to remove):
        host-side hooks consulted around every dispatch and at every
        tick — nothing traced ever sees it, so injection cannot change
        compiled programs or their budgets."""
        self._injector = injector
        if injector is not None:
            # Seeded nan_row faults pick their target among the active
            # rows, so the injector needs the engine back-reference
            # whichever way it was attached (here or injector.install).
            injector._engine = self

    def snapshot(self) -> EngineSnapshot:
        """Capture the engine's full host-side request state (between
        ``step`` calls): queued entries, every in-flight row as a resume
        entry carrying its tokens-so-far and pre-folded PRNG schedule,
        the rid counter, and undelivered results. Device state (the KV
        cache) is deliberately NOT captured — it is reconstructible from
        the prefixes, which is exactly what ``restore`` + the admission
        path do."""
        inflight = [
            self._pending_from_slot(s, bump=False)
            for s in self._slots if s is not None
        ]
        inflight.sort(key=lambda q: q.rid)
        queued = [
            dataclasses.replace(q, gen=list(q.gen)) for q in self._queue
        ]
        log_event(
            "snapshot", t=round(self._clock(), 6),
            inflight=len(inflight), queued=len(queued),
        )
        return EngineSnapshot(
            pending=inflight + queued,
            next_rid=self._next_rid,
            results=dict(self.results),
            stats=dict(self.counters),
        )

    def restore(self, snap: EngineSnapshot) -> None:
        """Load a ``snapshot`` into this (fresh, idle) engine — the
        crash-recovery path: after a device loss kills the old engine
        (and its donated cache), a rebuilt engine restores and its next
        ``step``s re-prefill every in-flight request from its
        tokens-so-far, continuing token-identically to an uninterrupted
        run (the per-request fold schedule rides in the entries).
        Buckets are recomputed against THIS engine's spec, so the
        snapshot survives a bucket-config change on rebuild."""
        if self.has_work() or self.results:
            raise RuntimeError(
                "restore requires a fresh idle engine (no queued/active "
                "work, no undelivered results)"
            )
        self._next_rid = snap.next_rid
        self.results.update(snap.results)
        for q in snap.pending:
            prefix = len(q.prompt) + len(q.gen)
            if prefix + (q.max_new - len(q.gen)) > self.max_len:
                raise ValueError(
                    f"snapshot entry rid {q.rid} needs "
                    f"{prefix + q.max_new - len(q.gen)} cache positions "
                    f"but this engine's max_len is {self.max_len}"
                )
            bucket = (
                self._resume_bucket(prefix)
                if q.gen
                else self.buckets.bucket_for(len(q.prompt))
            )
            # Session linkage is ENGINE-LOCAL and the restored engine's
            # tracker is fresh (sid 0 will be handed out again): keeping
            # the old sid would let a new session collide with it and
            # corrupt its transcript. The turn completes as a plain
            # request; its client re-opens (transcript-carrying
            # resubmission makes that lossless).
            self._queue.append(dataclasses.replace(
                q, bucket=bucket, gen=list(q.gen), session=None,
            ))
        log_event(
            "restore", t=round(self._clock(), 6),
            pending=len(snap.pending), next_rid=snap.next_rid,
        )

    def adopt(self, entries) -> dict[int, int]:
        """Take over queued/resume entries from ANOTHER engine — the
        router's failover path: when a replica dies, its host-side
        entries (in-flight rows already converted to resume entries
        carrying tokens-so-far + the pre-folded PRNG schedule) are
        adopted by survivors and continue BIT-IDENTICALLY, because the
        continuation depends only on the entry and the (shared) params,
        never on which engine runs it. Unlike ``restore`` this works on
        a BUSY engine: each entry is assigned THIS engine's next rid
        (the donor's rids would collide) and appended in the order
        given — adopted work queues behind traffic already admitted
        here, which is the deterministic choice a router can reason
        about. Returns {donor_rid: adopted_rid}; the caller (the
        router) owns the mapping."""
        entries = list(entries)
        # Validate EVERYTHING before touching the queue: a mixed batch
        # with one oversized entry must not half-adopt (the caller would
        # have no mapping for the entries already enqueued).
        for q in entries:
            if len(q.prompt) + q.max_new > self.max_len:
                raise ValueError(
                    f"adopted entry rid {q.rid} needs "
                    f"{len(q.prompt) + q.max_new} cache positions "
                    f"but this engine's max_len is {self.max_len}"
                )
        mapping: dict[int, int] = {}
        for q in entries:
            prefix = len(q.prompt) + len(q.gen)
            rid = self._next_rid
            self._next_rid += 1
            bucket = (
                self._resume_bucket(prefix)
                if q.gen
                else self.buckets.bucket_for(len(q.prompt))
            )
            # Donor session ids mean nothing here (and could collide
            # with a LIVE local session, corrupting its transcript):
            # adopted turns finish as plain requests; the router's
            # stickiness layer re-opens the session on the survivor.
            self._queue.append(dataclasses.replace(
                q, rid=rid, bucket=bucket, gen=list(q.gen), session=None,
            ))
            mapping[q.rid] = rid
        return mapping

    def peek_tokens(self, rid: int) -> np.ndarray | None:
        """Tokens-so-far for a live OR terminal request (prompt + every
        clean token generated to date) — the host-side progress read the
        SSE streaming front door polls between ticks. None for unknown
        rids; never touches device state."""
        for s in self._slots:
            if s is not None and s.rid == rid:
                return self._partial_tokens(s.prompt, s.generated)
        for q in self._queue:
            if q.rid == rid:
                return self._partial_tokens(q.prompt, q.gen)
        res = self.results.get(rid)
        return None if res is None else np.asarray(res.tokens)

    # -- scheduler internals -----------------------------------------------

    def _resume_bucket(self, length: int) -> int:
        """Smallest warmed prefill shape covering a resume prefix (the
        user buckets extended by max_len; exact length in exact mode)."""
        for b in self._prefill_buckets:
            if b >= length:
                return b
        return length

    def _prefill_keydata(self, req_keydata, g: int, greedy: bool):
        """The key the admission prefill must draw with so a resumed
        request's next token bit-matches the undisturbed run: token g of
        a request is sampled with fold_in(base_key, g - 1) (g = 0: the
        unfolded base key). Folded HOST-side — a rare, tiny dispatch —
        so the compiled prefill keeps its one uniform signature."""
        if greedy or g == 0:
            return req_keydata
        key = jax.random.wrap_key_data(jnp.asarray(req_keydata))
        return np.asarray(
            jax.random.key_data(jax.random.fold_in(key, g - 1))
        )

    def _pending_from_slot(
        self, s: _Slot, *, bump: bool, nan_retried: bool | None = None
    ) -> _Pending:
        """Convert an in-flight row to a resume entry: the clean tokens
        generated so far become the prefill prefix; ``bump`` charges one
        fault-resume against the request's retry budget."""
        g = len(s.generated)
        prefix = len(s.prompt) + g
        return _Pending(
            rid=s.rid, prompt=s.prompt, bucket=self._resume_bucket(prefix),
            max_new=s.max_new, eos_id=s.eos_id, greedy=s.greedy,
            t=s.t, k=s.k, p=s.p, keydata=s.keydata,
            prefill_keydata=self._prefill_keydata(s.keydata, g, s.greedy),
            deadline=s.deadline, gen=list(s.generated),
            retries=s.retries + (1 if bump else 0),
            nan_retried=s.nan_retried if nan_retried is None else nan_retried,
            tier=s.tier, session=s.session, resub_len=s.resub_len,
            tenant_slot=s.tenant_slot,
        )

    def _partial_tokens(self, prompt, gen) -> np.ndarray:
        return np.concatenate(
            [np.asarray(prompt, np.int32), np.asarray(gen, np.int32)]
        )

    def _finish(self, rid, state, tokens, reason,
                finished: list[int] | None = None) -> None:
        self.results[rid] = RequestResult(
            rid=rid, state=state, tokens=tokens, reason=reason
        )
        self.counters[state.lower()] += 1
        if finished is not None:
            finished.append(rid)
        log_event(
            "retire", rid=rid, state=state, t=round(self._clock(), 6),
            n_tokens=len(tokens), reason=reason or None,
        )

    def _finish_pending(self, q: _Pending, state, reason,
                        finished=None) -> None:
        self._finish(
            q.rid, state, self._partial_tokens(q.prompt, q.gen), reason,
            finished,
        )

    def _finish_slot(self, s: _Slot, state, reason, finished=None) -> None:
        self._finish(
            s.rid, state, self._partial_tokens(s.prompt, s.generated),
            reason, finished,
        )

    def _requeue(self, pendings) -> None:
        """Merge resume/rewound entries back into the admission queue in
        ascending-rid order — rids are assigned at submit, so rid order
        IS global FIFO order: a resumed old request re-admits before
        younger traffic, keeping scheduling deterministic under faults."""
        if not pendings:
            return
        items = sorted(
            list(self._queue) + list(pendings), key=lambda q: q.rid
        )
        self._queue = collections.deque(items)

    def _expire(self, finished: list[int]) -> None:
        now = self._clock()
        overdue = [
            q for q in self._queue
            if q.deadline is not None and now >= q.deadline
        ]
        for q in overdue:
            self._queue.remove(q)
            self._finish_pending(
                q, EXPIRED,
                f"deadline passed at t={now:.3f} while queued", finished,
            )
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline is not None and now >= s.deadline:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._finish_slot(
                    s, EXPIRED,
                    f"deadline passed at t={now:.3f} mid-decode", finished,
                )

    def _queue_key(self, q: _Pending):
        """Admission order: tier rank, then (INTERACTIVE only) earliest
        deadline, then rid — scheduler.queue_key. An all-STANDARD queue
        sorts exactly by rid, i.e. the pre-tier FIFO (regression-pinned
        in tests/test_serving_scenarios.py)."""
        return queue_key(q.tier, q.deadline, q.rid)

    def _admit(self, params, finished: list[int]) -> None:
        free = [i for i, s in enumerate(self._slots) if s is None]
        n = min(len(free), len(self._queue))
        if not n:
            return
        admitted = sorted(self._queue, key=self._queue_key)[:n]
        for q in admitted:
            self._queue.remove(q)
        # Priority-then-FIFO admission (interactive bypasses the queue
        # head; an all-standard stream keeps the exact pre-tier order);
        # arrivals sharing a bucket prefill as one batched dispatch
        # (group padded to the next allowed size).
        by_bucket: dict[int, list[tuple[_Pending, int]]] = {}
        for req in admitted:
            by_bucket.setdefault(req.bucket, []).append(
                (req, free.pop(0))
            )
        groups = list(by_bucket.items())
        for gi, (bucket, group) in enumerate(groups):
            if not self._prefill_group(params, bucket, group, finished):
                # Dispatch failed: recovery requeued this group and every
                # in-flight row; rewind the not-yet-dispatched groups
                # untouched (no retry charge — they were never at risk)
                # and stop admitting this tick.
                rest = [
                    pend for _, g in groups[gi + 1:] for pend, _ in g
                ]
                self._requeue(rest)
                return

    def _prefill_group(self, params, bucket, group, finished) -> bool:
        """One bucket's admission dispatch. Returns False when the
        dispatch failed (recovery already ran)."""
        n = len(group)
        npad = next(g for g in self._groups if g >= n)
        # Pad the group by DUPLICATING entry 0 (same row index, same
        # data): the overlapping scatter writes are bit-identical, and
        # the duplicate's sampled token is discarded.
        idx = list(range(n)) + [0] * (npad - n)
        prompts = np.zeros((npad, bucket), np.int32)
        plens = np.zeros((npad,), np.int32)
        rows = np.zeros((npad,), np.int32)
        greedy = np.zeros((npad,), np.bool_)
        t = np.ones((npad,), np.float32)
        k = np.full((npad,), self.cfg.vocab_size, np.int32)
        p = np.full((npad,), 2.0, np.float32)
        keydata = np.zeros((npad, self._key_words), np.uint32)
        tenants = np.zeros((npad,), np.int32)
        for j, i in enumerate(idx):
            req, row = group[i]
            prefix = self._partial_tokens(req.prompt, req.gen)
            prompts[j, : prefix.shape[0]] = prefix
            plens[j] = prefix.shape[0]
            rows[j] = row
            greedy[j] = req.greedy
            t[j], k[j], p[j] = req.t, req.k, req.p
            keydata[j] = req.prefill_keydata
            tenants[j] = req.tenant_slot
        res = self._dispatch(
            "prefill", params, [req for req, _ in group], finished,
            jnp.asarray(prompts), jnp.asarray(plens),
            jnp.asarray(rows), None, jnp.asarray(greedy), jnp.asarray(t),
            jnp.asarray(k), jnp.asarray(p), jnp.asarray(keydata),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return False
        toks, bad = res
        for i, (req, row) in enumerate(group):
            if bad[i]:
                self._quarantine_pending(req, finished)
                continue
            self._slots[row] = _Slot(
                rid=req.rid, prompt=req.prompt, max_new=req.max_new,
                eos_id=req.eos_id, pos=int(plens[i]), fold=len(req.gen),
                generated=list(req.gen) + [int(toks[i])],
                greedy=req.greedy, t=req.t, k=req.k, p=req.p,
                keydata=req.keydata, deadline=req.deadline,
                retries=req.retries, nan_retried=req.nan_retried,
                tier=req.tier, session=req.session,
                resub_len=req.resub_len, tenant_slot=req.tenant_slot,
            )
            log_event(
                "admit", rid=req.rid, row=row, bucket=bucket,
                resume_prefix=len(req.gen) or None,
                t=round(self._clock(), 6),
            )
            self._maybe_retire(row, finished)
        return True

    # -- speculation (host side) -------------------------------------------

    def _draft_tokens(self, s: _Slot) -> np.ndarray:
        """Up to ``speculative_k`` draft tokens for one active row —
        prompt-lookup over the row's tokens-so-far (or the engine's
        ``draft_hook``), capped so every COMMITTABLE token's position
        stays inside the row's budget and the cache extent. Sampled
        rows draft nothing (exact sampled speculation needs rejection-
        sampling corrections — out of scope, models/speculative.py);
        they still ride the same program with zero-draft lanes."""
        if not s.greedy:
            return _EMPTY_DRAFT
        cap = min(
            self.speculative_k,
            s.max_new - len(s.generated) - 1,
            self.max_len - s.pos - 1,
        )
        if cap <= 0:
            return _EMPTY_DRAFT
        hist = self._partial_tokens(s.prompt, s.generated)
        if self._draft_hook is not None:
            d = np.asarray(
                self._draft_hook(hist, cap), np.int32
            ).reshape(-1)[:cap]
            # Hook output is advisory: clip to the vocab so a buggy
            # hook can cost speed (rejected drafts) but never an OOB
            # embedding lookup.
            return np.clip(d, 0, self.cfg.vocab_size - 1)
        from pytorch_distributed_tpu.models.speculative import (
            prompt_lookup_draft,
        )

        return prompt_lookup_draft(hist, cap, ngram=self.spec_ngram)

    def _commit_spec(self, row: int, s: _Slot, out_row: np.ndarray,
                     n_acc: int, n_draft: int, finished) -> None:
        """Commit one row's verified window: accepted drafts plus the
        model's bonus/correction token, clipped at EOS and the row's
        budget. Rejected drafts are rolled back by simply not advancing
        ``pos`` past the commit — their K/V garbage sits beyond the
        row's depth, masked by the pos discipline and overwritten by
        later writes (on the paged engine it is confined to the row's
        private tail page)."""
        committed = 0
        for tok in out_row[: n_acc + 1]:
            s.generated.append(int(tok))
            s.pos += 1
            s.fold += 1
            committed += 1
            if len(s.generated) >= s.max_new or (
                s.eos_id is not None and int(tok) == s.eos_id
            ):
                break  # EOS inside the window: later lanes discarded
        self.counters["drafted_tokens"] += n_draft
        self.counters["accepted_tokens"] += committed - 1
        self.counters["spec_commits"] += 1
        if n_draft:
            log_event(
                "draft_accept", rid=s.rid, drafted=n_draft,
                accepted=committed - 1, t=round(self._clock(), 6),
            )
        self._maybe_retire(row, finished)

    def _decode_tick_spec(self, params, finished: list[int]) -> None:
        """The speculative twin of ``_decode_tick``: every active row's
        lane-0 token plus its host drafts go through ONE k+1-wide
        verify forward; per-row accept lengths come back traced."""
        b, width = self.slots, self.speculative_k + 1
        toks = np.zeros((b, width), np.int32)
        n_draft = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        folds = np.zeros((b,), np.int32)
        greedy = np.ones((b,), np.bool_)
        t = np.ones((b,), np.float32)
        k = np.full((b,), self.cfg.vocab_size, np.int32)
        p = np.full((b,), 2.0, np.float32)
        keydata = np.zeros((b, self._key_words), np.uint32)
        tenants = np.zeros((b,), np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue  # free rows verify garbage the host discards
            drafts = self._draft_tokens(s)
            toks[i, 0] = s.generated[-1]
            toks[i, 1 : 1 + len(drafts)] = drafts
            n_draft[i] = len(drafts)
            pos[i] = s.pos
            folds[i] = s.fold
            greedy[i] = s.greedy
            t[i], k[i], p[i] = s.t, s.k, s.p
            keydata[i] = s.keydata
            tenants[i] = s.tenant_slot
        res = self._dispatch(
            "decode_spec_step", params, None, finished,
            jnp.asarray(toks), None, jnp.asarray(pos),
            jnp.asarray(folds), jnp.asarray(greedy), jnp.asarray(t),
            jnp.asarray(k), jnp.asarray(p), jnp.asarray(keydata),
            jnp.asarray(n_draft),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return
        out, n_acc, bad = res
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if bad[i]:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._quarantine_slot(s, i, finished)
                continue
            self._commit_spec(
                i, s, out[i], int(n_acc[i]), int(n_draft[i]), finished
            )

    def _decode_tick(self, params, finished: list[int]) -> None:
        if self.speculative_k:
            return self._decode_tick_spec(params, finished)
        b = self.slots
        toks = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        folds = np.zeros((b,), np.int32)
        greedy = np.ones((b,), np.bool_)
        t = np.ones((b,), np.float32)
        k = np.full((b,), self.cfg.vocab_size, np.int32)
        p = np.full((b,), 2.0, np.float32)
        keydata = np.zeros((b, self._key_words), np.uint32)
        tenants = np.zeros((b,), np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue  # free rows decode garbage the host discards
            toks[i] = s.generated[-1]
            pos[i] = s.pos
            folds[i] = s.fold
            greedy[i] = s.greedy
            t[i], k[i], p[i] = s.t, s.k, s.p
            keydata[i] = s.keydata
            tenants[i] = s.tenant_slot
        res = self._dispatch(
            "decode_step", params, None, finished, jnp.asarray(toks),
            None, jnp.asarray(pos), jnp.asarray(folds),
            jnp.asarray(greedy), jnp.asarray(t), jnp.asarray(k),
            jnp.asarray(p), jnp.asarray(keydata),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return
        out, bad = res
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if bad[i]:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._quarantine_slot(s, i, finished)
                continue
            s.generated.append(int(out[i]))
            s.pos += 1
            s.fold += 1
            self._maybe_retire(i, finished)

    def _quarantine_pending(self, req: _Pending, finished) -> None:
        """Non-finite logits in an admission prefill: the garbage token
        is discarded and the request retried once over a freshly
        re-prefilled row, then FAILED."""
        self.counters["nan_quarantines"] += 1
        if req.nan_retried:
            self._finish_pending(
                req, FAILED,
                "non-finite logits persisted after one quarantine retry "
                "(prefill)", finished,
            )
            return
        log_event(
            "quarantine", rid=req.rid, phase="prefill",
            t=round(self._clock(), 6),
        )
        self._requeue([dataclasses.replace(
            req, gen=list(req.gen), nan_retried=True
        )])

    def _quarantine_slot(self, s: _Slot, row: int, finished,
                         phase: str = "decode") -> None:
        """Non-finite logits on an active row: free the row (neighbours
        untouched — per-row masking means its re-prefill reads only what
        it rewrites), requeue its CLEAN prefix for one fresh re-prefill,
        then FAILED on recurrence. ``phase`` labels the lifecycle log
        and failure reason (the paged engine's chunked prefill
        quarantines through here too)."""
        self.counters["nan_quarantines"] += 1
        if s.nan_retried:
            self._finish_slot(
                s, FAILED,
                "non-finite logits persisted after one quarantine retry "
                f"({phase})", finished,
            )
            return
        log_event(
            "quarantine", rid=s.rid, phase=phase, row=row,
            t=round(self._clock(), 6),
        )
        self._requeue([
            self._pending_from_slot(s, bump=False, nan_retried=True)
        ])

    def _dispatch(self, kind, params, group_pendings, finished, *args):
        """Run ``kind`` with the engine cache spliced in at its donated
        argnum, consulting the fault injector around the call. Returns
        (tokens, bad) as host arrays, or None after a RECOVERED failure.

        Any failure — the program raising, or the result dropped in
        transit — consumed the donated cache, so every in-flight row's
        K/V is gone: recovery converts them ALL to resume entries
        (re-prefilled from tokens-so-far on a later tick), charges one
        retry against each, and backs off exponentially; queued requests
        are untouched. ``dispatch_retries`` consecutive failures raise
        ``DispatchFailure`` with the state already consistent."""
        cache_at = self.CACHE_ARGNUM[kind] - 1  # args exclude params here
        args = list(args)
        args[cache_at] = self._take_cache()
        inj = self._injector
        try:
            if inj is not None:
                inj.before_dispatch(kind, self._ticks)
            # Programs return (tokens, ..., bad, cache): the spec step
            # carries the per-row accept lengths between tokens and the
            # sentinel; the injector hooks see (tokens, bad) whichever
            # program ran.
            *outs, cache = self.program(kind)(params, *args)
            if inj is not None:
                tok, bad = inj.after_dispatch(
                    kind, self._ticks, outs[0], outs[-1]
                )
                outs = [tok, *outs[1:-1], bad]
        except Exception as err:
            # Exception, not BaseException: KeyboardInterrupt/SystemExit
            # must abort the serving loop, not masquerade as a transient
            # device fault and get retried.
            self._recover_dispatch_failure(
                kind, err, group_pendings or [], finished
            )
            return None
        self._cache = cache
        self._fail_streak = 0
        # repolint: allow(blocking-sync-in-tick) — the adjudicated
        # dispatch-boundary read: the scheduler needs this tick's tokens
        # and sentinel ON HOST to route/retire rows before it can build
        # the next dispatch, so exactly one sync per tick is the design
        # (everything upstream stays async; the cache stays on device).
        return tuple(np.asarray(o) for o in outs)

    def _recover_dispatch_failure(self, kind, err, group_pendings,
                                  finished) -> None:
        self.counters["dispatch_failures"] += 1
        self._fail_streak += 1
        log_event(
            "dispatch_fail", kind=kind, tick=self._ticks,
            streak=self._fail_streak, error=type(err).__name__,
            t=round(self._clock(), 6),
        )
        lost = []
        for s in self._slots:
            if s is not None:
                lost.append(self._pending_from_slot(s, bump=True))
                self._on_slot_freed(s)
        self._slots = [None] * self.slots
        lost += [
            dataclasses.replace(q, gen=list(q.gen), retries=q.retries + 1)
            for q in group_pendings
        ]
        kept = []
        for q in lost:
            if q.retries > self.request_retries:
                self._finish_pending(
                    q, FAILED,
                    f"dispatch failed ({type(err).__name__}) and the "
                    f"request exhausted its {self.request_retries} "
                    "fault-resume retries", finished,
                )
            else:
                self.counters["resumes"] += 1
                kept.append(q)
        self._requeue(kept)
        if (
            self.dispatch_retries is not None
            and self._fail_streak > self.dispatch_retries
        ):
            raise DispatchFailure(
                f"{self._fail_streak} consecutive dispatch failures "
                f"(> dispatch_retries {self.dispatch_retries}); engine "
                "state is consistent — every in-flight request was "
                "requeued (or FAILED past its retry budget); snapshot() "
                "and rebuild, or step again later"
            ) from err
        if self._fail_streak > 0 and self.retry_backoff_s > 0:
            self._sleep(
                self.retry_backoff_s * (2 ** (self._fail_streak - 1))
            )

    def _maybe_retire(self, row: int, finished: list[int]) -> None:
        s = self._slots[row]
        hit_eos = s.eos_id is not None and s.generated[-1] == s.eos_id
        if len(s.generated) < s.max_new and not hit_eos:
            return
        # Retirement is pure host bookkeeping: the row's K/V stays in
        # place (dirty) and the next admission masks it out.
        self._slots[row] = None
        self._on_slot_freed(s)
        self._finish_slot(s, DONE, "", finished)

    def _on_slot_freed(self, s: _Slot) -> None:
        """Hook: called whenever an occupied slot leaves the slot list
        (retire / abort / expire / quarantine / dispatch-failure
        conversion). The dense engine has nothing to do — a freed row's
        K/V just sits dirty in its own row; the paged subclass releases
        the row's page references here."""

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict[str, Any]:
        """Uniform engine-state snapshot: scheduler occupancy (queue
        depth, active rows, free slots) + page-pool pressure (None on
        non-paged engines — same keys everywhere, so the router's
        admission scoring reads one schema regardless of which engine
        backs a replica) + a copy of the monotonic ``counters``. Pure
        host bookkeeping; never dispatches."""
        free_slots = sum(1 for s in self._slots if s is None)
        by_tier = {name: 0 for name in PRIORITIES}
        for q in self._queue:
            by_tier[TIER_NAME[q.tier]] += 1
        return {
            "engine": type(self).__name__,
            "role": self.role,
            "device_ids": self.device_ids(),
            "queue_depth": len(self._queue),
            "queue_depth_by_tier": by_tier,
            "slots": self.slots,
            "active_rows": self.slots - free_slots,
            "free_slots": free_slots,
            "pool_pages": None,
            "free_pages": None,
            "pages_in_use": None,
            "session_pinned_pages": None,
            "sessions": None,
            "prefix_hit_rate": None,
            "kv_quant": "none",
            "speculative_k": self.speculative_k,
            "spec_accept_rate": _spec_accept_rate(self.counters),
            "counters": dict(self.counters),
        }

    def compile_count(self) -> int:
        """Total compiled executables across both programs: ONE
        decode(_spec)_step + one prefill per (bucket, group) shape
        served. The churn tests assert this stays flat across
        admissions and retirements at a fixed slot count."""
        return sum(p._cache_size() for p in self._programs.values())

    def _bytes_per_position(self) -> int:
        """K+V bytes one GLOBAL cache position costs across all layers
        (see ``_kv_bytes_per_position``; the paged subclass switches the
        figure when its pool is quantized)."""
        return _kv_bytes_per_position(self.cfg)

    def cache_hbm_bytes(self) -> dict[str, int]:
        """Allocated KV-cache HBM (the dense engine preallocates
        slots x max_len positions whether rows are deep or not — the
        number the paged engine's pool is benched against)."""
        n = self.slots * self.max_len
        b = n * self._bytes_per_position()
        return {"allocated": b, "peak_in_use": b}

    def example_args(self, kind: str, params, *, bucket: int | None = None,
                     group: int = 1, cache: decode.Cache | None = None):
        """Example argument tuple for lowering/auditing ``kind`` — the
        shapes ``step`` dispatches with. ``cache=None`` allocates a
        fresh one (callers doing real dispatches should pass
        ``self._take_cache()`` and pocket the returned buffer)."""
        if cache is None:
            cache = self._new_cache()
        if kind == "prefill":
            b = bucket or (
                self.buckets.buckets[0] if self.buckets.buckets else 4
            )
            npad = next(g for g in self._groups if g >= group)
            return (
                params,
                jnp.zeros((npad, b), jnp.int32),
                jnp.ones((npad,), jnp.int32),
                jnp.zeros((npad,), jnp.int32),
                cache,
                jnp.ones((npad,), jnp.bool_),
                jnp.ones((npad,), jnp.float32),
                jnp.full((npad,), self.cfg.vocab_size, jnp.int32),
                jnp.full((npad,), 2.0, jnp.float32),
                jnp.zeros((npad, self._key_words), jnp.uint32),
            ) + self._lora_dispatch_args(np.zeros((npad,), np.int32))
        if kind == "decode_step":
            b = self.slots
            return (
                params,
                jnp.zeros((b,), jnp.int32),
                cache,
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), self.cfg.vocab_size, jnp.int32),
                jnp.full((b,), 2.0, jnp.float32),
                jnp.zeros((b, self._key_words), jnp.uint32),
            ) + self._lora_dispatch_args(np.zeros((b,), np.int32))
        if kind == "decode_spec_step":
            b, width = self.slots, self.speculative_k + 1
            return (
                params,
                jnp.zeros((b, width), jnp.int32),
                cache,
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), self.cfg.vocab_size, jnp.int32),
                jnp.full((b,), 2.0, jnp.float32),
                jnp.zeros((b, self._key_words), jnp.uint32),
                jnp.zeros((b,), jnp.int32),
            ) + self._lora_dispatch_args(np.zeros((b,), np.int32))
        raise KeyError(f"unknown batched program kind {kind!r}")

    def verify_donation(self, params) -> dict[str, dict]:
        """Prove the slot cache actually aliases in/out of every batched
        program this engine dispatches (strict mode of the donation
        audit) — the engine-side twin of ``DecodeEngine.verify_donation``.
        A rejected alias would double-buffer the whole (slots, max_len)
        cache EVERY TOKEN."""
        from pytorch_distributed_tpu.analysis.audit import check_donation

        params = self._place_params(params)
        stats_all: dict[str, dict] = {}
        for kind in self._program_kinds():
            args = self.example_args(kind, params)
            compiled = self.program(kind).lower(*args).compile()
            findings, stats = check_donation(
                compiled.as_text(), args, (self.CACHE_ARGNUM[kind],),
                strict=True,
            )
            stats_all[kind] = stats
            if findings:
                raise RuntimeError(
                    f"batched engine program {kind!r} ({self.mode}): "
                    "donated slot KV cache does not fully alias in the "
                    f"compiled executable — {findings[0].message}"
                )
        return stats_all


@dataclasses.dataclass
class _PagedSlot(_Slot):
    """One occupied row of the PAGED slot batch. Extends ``_Slot`` with
    the row's page bookkeeping and chunked-prefill progress: ``pos``
    doubles as the prefill cursor (next position to prefill) until it
    reaches ``prefill_len``, after which the row is decode-ready and
    ``pos`` means what it means on the dense engine (next KV write
    offset). Dataclass-inheritance ordering forces defaults here; the
    engine always fills them at admission."""

    prefix: np.ndarray | None = None  # prompt + resume tokens to prefill
    prefill_len: int = 0  # len(prefix)
    table: np.ndarray | None = None  # [max_pages] int32 page ids (0=scratch)
    pids: list = dataclasses.field(default_factory=list)  # pages held
    n_pages: int = 0  # allocated table entries
    prefill_keydata: np.ndarray | None = None  # key for the final chunk draw
    resume_base: int = 0  # len(resume gen) riding ahead of fresh tokens
    chain_key: str = ""  # prefix-cache chain key at pos (1 digest/publish)

    @property
    def ready(self) -> bool:
        return self.pos >= self.prefill_len


@dataclasses.dataclass
class KVHandoff:
    """One finished prefill leaving a PREFILL worker (disaggregated
    serving): the device pages (+ block-table order, + per-row int8
    scale leaves riding the same tree) and every host field a decode
    worker needs to continue the row BIT-IDENTICALLY to a colocated
    run. ``entry`` doubles as the fault fallback: it is the ordinary
    PR-6 resume entry for the same row, so a handoff that never
    completes (either side dying) degrades to the existing
    resume/failover path with zero new machinery."""

    entry: Any            # _Pending resume entry (fault fallback + host fields)
    pages: Any            # device tree, per leaf [L, max_pages, ...]
    n_pages: int          # real (non-padding) table entries
    pos: int              # committed depth (== prefill_len on export)
    fold: int             # the row's PRNG fold cursor
    generated: list       # resume gen + the final-chunk sampled token
    prefill_len: int
    resume_base: int
    page_size: int
    max_pages: int
    kv_quant: str
    src_rid: int          # engine-local rid on the SOURCE engine
    useful_bytes: int     # n_pages x page_size x bytes/position
    wire_bytes: int       # padded tree bytes actually shipped
    export_s: float       # device time of the kv_export gather


class PagedBatchedDecodeEngine(BatchedDecodeEngine):
    """Continuous batching over a PAGED KV cache: the block-pool refactor
    of ``BatchedDecodeEngine`` (ROADMAP direction 1 — the vLLM move).

    The dense engine's ``(slots, max_len)`` cache charges every row
    O(max_len) HBM and O(max_len) attention regardless of its depth.
    Here the cache is a flat pool of fixed-size PAGES —
    ``[L, pool_pages, page_size, Hkv, D]`` — and each row holds a BLOCK
    TABLE of page ids instead of a dedicated row. Three consequences,
    all machine-checked:

    - **HBM scales with the pool, not slots x max_len**: ``slots`` can
      exceed what uniform-max_len rows would fit, because real rows are
      rarely max_len deep. Pool exhaustion mid-decode PREEMPTS the
      youngest active request (clean resume entry, re-admitted when
      pages free — "queued last, preempted first"), so overcommit
      degrades to queueing, never to a hang or corruption; admission
      additionally defers when the pool cannot cover a prompt.
    - **Prefix sharing**: identical prompt prefixes are stored ONCE
      (serving/block_pool.py: chunk-chained sha1 keys, refcounted pages,
      LRU retention after the last reference drops), copy-on-write by
      construction — shared pages are never written, forks land on
      private pages. Hit counts ride the lifecycle log and
      ``pool.stats``.
    - **Chunked prefill**: an admission is fed through the tick in
      ``prefill_chunk``-token chunks (one chunk per row per tick), so a
      long prompt never stalls in-flight rows for its whole prefill —
      the per-tick prefill cost is bounded by chunk x group, and decode
      ticks interleave. The chunk is the prefill compile shape (no
      prompt buckets: compile set = groups x ONE chunk shape + one
      decode step).

    Everything traced stays fixed-shape: block tables are [slots,
    max_pages] int32 OPERANDS (values change per tick, shapes never), so
    the PR-5 zero-steady-state-compile contract and the PR-6 fault
    model (quarantine, dispatch recovery, snapshot/replay) carry over
    unchanged — a failed dispatch consumed the donated POOL, so recovery
    additionally resets the block pool and prefix cache (the content the
    cache keys pointed at is gone). Attention defaults to the pure-XLA
    ``gather_pages`` fallback (bit-identical math to the dense engine —
    the paged-vs-dense token-equality pins in
    tests/test_serving_paged.py rely on it); on TPU,
    ``paged_attention="kernel"`` dispatches the Pallas paged-attention
    decode kernel (ops/paged_kernel.py), whose per-row cost scales with
    the row's page count.

    Knobs: ``page_size`` (tokens per KV page; must divide ``max_len``),
    ``pool_pages`` (pool capacity incl. the reserved scratch page 0;
    default = dense-equivalent ``slots * max_len/page_size + 1``),
    ``prefill_chunk`` (chunked-prefill quantum; page-multiple dividing
    ``max_len``, default = largest such <= 64).

    **Speculation on pages** (``speculative_k`` — see
    ``BatchedDecodeEngine``): rejection rollback is just truncating the
    row's depth. The verify window writes K/V for all k+1 lanes, but
    every write lands at positions >= the row's committed ``pos`` —
    strictly past any shared-prefix or session-pinned page (those cover
    positions < the row's first private chunk), so the sha1
    chunk-chained prefix cache never sees speculative state; committed
    lanes occupy the row's private tail pages (grown best-effort, never
    by preemption — ``_grow_for_drafts``), rejected lanes are masked
    garbage overwritten by later writes, and lanes past the table
    redirect to the scratch page. With int8 pages the per-token scales
    make rollback free: appending (and re-appending over garbage) can
    never re-quantize a neighbouring token. Multi-token verify windows
    use the XLA gather fallback even under ``paged_attention="kernel"``
    (the Pallas kernel is single-query; a multi-query twin is future
    surface).
    """

    # kv_import is the ONLY kv-handoff program that donates: it scatters
    # imported pages into this worker's pool in place. kv_export is a
    # pure gather and deliberately does NOT donate (the source pool must
    # stay valid until the router confirms the import landed — see
    # ``export_handoff``), so it has no entry here. Its argnums count
    # the program's own operands (kv programs take no params).
    CACHE_ARGNUM = {
        "prefill": 5, "decode_step": 2, "decode_spec_step": 2,
        "kv_import": 2,
    }

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        slots: int,
        max_len: int,
        page_size: int = 16,
        pool_pages: int | None = None,
        prefill_chunk: int | None = None,
        paged_attention: str = "gather",
        kv_quant: str = "none",
        mesh_cfg: MeshConfig | None = None,
        session_pin_budget_pages: int | None = None,
        batch_admit_free_frac: float = 0.25,
        role: str = "colocated",
        **kw,
    ) -> None:
        if page_size < 1 or max_len % page_size:
            raise ValueError(
                f"page_size ({page_size}) must be a positive divisor of "
                f"max_len ({max_len}): the block table addresses exactly "
                "max_len/page_size pages per row, and a ragged final "
                "page would silently truncate the last "
                f"{max_len % page_size if page_size >= 1 else 0} cache "
                "positions — pick page_size from the divisors of max_len"
            )
        super().__init__(
            cfg, slots=slots, max_len=max_len, buckets=None,
            mesh_cfg=mesh_cfg, **kw,
        )
        self.page_size = int(page_size)
        self.max_pages = max_len // page_size
        if prefill_chunk is None:
            # Largest page-multiple <= 64 that divides max_len. The
            # chunk is BOTH the prefill quantum (per-tick prefill work
            # is bounded by chunk x group) and the prefix-sharing
            # granularity (block_pool caches chunk-chained prefixes), so
            # the default leans small; deployments with long shared
            # system prompts and long arrivals tune it per traffic.
            prefill_chunk = page_size
            while (
                prefill_chunk * 2 <= min(64, max_len)
                and max_len % (prefill_chunk * 2) == 0
            ):
                prefill_chunk *= 2
        if (
            prefill_chunk < page_size
            or prefill_chunk % page_size
            or max_len % prefill_chunk
        ):
            raise ValueError(
                f"prefill_chunk ({prefill_chunk}) must be a multiple of "
                f"page_size ({page_size}) that divides max_len "
                f"({max_len}) — chunk starts are page-aligned and the "
                "padded final chunk must stay inside the row's table"
            )
        self.chunk = int(prefill_chunk)
        if pool_pages is None:
            pool_pages = slots * self.max_pages + 1
        if pool_pages < self.max_pages + 1:
            raise ValueError(
                f"pool_pages ({pool_pages}) must be >= max_len/page_size "
                f"+ 1 = {self.max_pages + 1} (one full-length row plus "
                "the scratch page), or a single deep request could "
                "never be served"
            )
        self.pool_pages = int(pool_pages)
        from pytorch_distributed_tpu.serving.block_pool import BlockPool

        self.pool = BlockPool(self.pool_pages, self.page_size, self.chunk)
        if paged_attention == "auto":
            paged_attention = (
                "kernel" if jax.devices()[0].platform == "tpu"
                else "gather"
            )
        if paged_attention not in ("gather", "kernel", "kernel_interpret"):
            raise ValueError(
                f"paged_attention must be 'auto', 'gather', 'kernel' or "
                f"'kernel_interpret', got {paged_attention!r}"
            )
        self._paged_impl = paged_attention
        self.kv_quant = _check_quant_arg("kv_quant", kv_quant)
        # Disaggregation role (ROADMAP direction 1): "colocated" is the
        # historic engine (prefill + decode on one worker); "prefill"
        # runs chunked prefill only and parks finished rows for
        # ``export_handoff``; "decode" accepts rows only via
        # ``import_handoff``/``adopt`` and never prefills fresh prompts.
        self.role = _check_role(role)
        self.counters["preemptions"] = 0
        self.counters["preempt_priority"] = 0
        self.counters["batch_yield_ticks"] = 0
        self.counters["handoffs_out"] = 0
        self.counters["handoffs_in"] = 0
        if not 0.0 <= batch_admit_free_frac <= 1.0:
            raise ValueError(
                f"batch_admit_free_frac must be in [0, 1], got "
                f"{batch_admit_free_frac} (the free-page fraction below "
                "which BATCH-tier requests stop admitting)"
            )
        self.batch_admit_free_frac = float(batch_admit_free_frac)
        from pytorch_distributed_tpu.serving.session import SessionTracker

        # Session retention pins at most half the pool by default and
        # evict_idle sheds loudly past the budget. Pins can still cover
        # capacity a queued request needs when every pinned session has
        # a turn in flight (inflight pins are unevictable) — _admit's
        # no-live-rows go-around below keeps that from stalling the
        # queue for good.
        self._sessions = SessionTracker(
            self.pool,
            pin_budget_pages=(
                (self.pool_pages - 1) // 2
                if session_pin_budget_pages is None
                else session_pin_budget_pages
            ),
            clock=self._clock,
        )
        log_event(
            "pool_build",
            quant=self.kv_quant,
            pool_pages=self.pool_pages,
            page_size=self.page_size,
            prefill_chunk=self.chunk,
            slots=self.slots,
            pool_hbm_bytes=(
                self.pool_pages * self.page_size
                * _kv_bytes_per_position(cfg, self.kv_quant)
            ),
        )

    # -- cache -------------------------------------------------------------

    def _cache_pspec(self) -> dict:
        """Per-leaf PartitionSpecs for the paged cache under TP: value
        pools shard their Hkv dim; the int8 layout's scale pools shard
        the same dim (their last — scales live with their heads)."""
        from jax.sharding import PartitionSpec as P

        spec = {
            "k": P(None, None, None, "tensor", None),
            "v": P(None, None, None, "tensor", None),
        }
        if self.kv_quant == "int8":
            s = P(None, None, None, "tensor")
            spec.update(k_scale=s, v_scale=s)
        return spec

    def _new_cache(self) -> decode.Cache:
        self.counters["cache_allocs"] += 1
        if self.mode == "tp":
            full = decode.init_paged_cache(
                self.cfg, self.pool_pages, self.page_size,
                kv_quant=self.kv_quant,
            )
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = jax.tree.map(
                lambda s: NamedSharding(self._mesh, s),
                self._cache_pspec(),
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.device_put(full, sharding)
        cache = decode.init_paged_cache(
            self.cfg, self.pool_pages, self.page_size, n_kv=self._n_kv,
            kv_quant=self.kv_quant,
        )
        if self.device is not None:
            # Committed inputs pin every jitted program's outputs to the
            # same chip (see the dense engine's _new_cache).
            cache = jax.device_put(cache, self.device)
        return cache

    def _bytes_per_position(self) -> int:
        return _kv_bytes_per_position(self.cfg, self.kv_quant)

    def cache_hbm_bytes(self) -> dict[str, int]:
        """Allocated pool HBM + the peak actually referenced by live
        rows (pages_in_use x page_size positions) — the numbers
        ``decode_bench --serving-paged`` reports against the dense
        engine's slots x max_len."""
        per = self._bytes_per_position()
        return {
            "allocated": self.pool_pages * self.page_size * per,
            "peak_in_use": (
                self.pool.stats["peak_pages_in_use"] * self.page_size * per
            ),
        }

    def stats(self) -> dict[str, Any]:
        """The uniform snapshot with the paged fields filled in: page
        pressure (free/in-use against the pool) is the second admission
        signal the router weighs next to queue depth — closing the gap
        where ``pool.stats`` was a paged-only side channel."""
        out = super().stats()
        ps = self.pool.stats
        out.update(
            # pool_pages is the EFFECTIVE page capacity: a quantized
            # pool provisioned at byte-equal HBM holds ~4x the f32
            # pages, and that real capacity is the router's page-
            # pressure denominator (pages_in_use / pool_pages) — scoring
            # in bytes would starve-exclude a quantized replica that
            # still has page headroom (regression-pinned in
            # tests/test_serving_quant.py).
            pool_pages=self.pool_pages,
            free_pages=self.pool.free_pages(),
            pages_in_use=self.pool.pages_in_use(),
            # Session retention's capacity cost: pages held ONLY by a
            # pin. The router's least-loaded scoring adds these to page
            # pressure, so a session-heavy replica is deprioritized
            # BEFORE it starts preempting for its pinned residents.
            session_pinned_pages=self.pool.pinned_pages(),
            sessions=len(self._sessions),
            prefix_hit_rate=round(
                ps["prefix_hits"] / max(1, ps["prefix_queries"]), 4
            ),
            kv_quant=self.kv_quant,
        )
        out["counters"]["session_evictions"] = self._sessions.evictions
        return out

    # -- programs ----------------------------------------------------------

    def _forward_paged(self, params, ids, cache, pos, tables, lora=None):
        kwargs = {
            "block_tables": tables,
            "paged_impl": self._paged_impl,
            "kv_quant": self.kv_quant,
        }
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        if lora:
            kwargs["lora"] = lora
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self):
        """The two paged program bodies. Same traced-everything
        discipline as the dense engine, plus the [B, max_pages] block
        tables as int32 operands; the NaN sentinel and sampling are
        shared with the dense bodies so they can never drift."""

        def prefill(params, chunks, valid, start, tables, cache,
                    greedy, t, k, p, keydata, *lora):
            # One CHUNK per row: tokens chunks[:, :valid] run at
            # positions start..start+valid-1 (pad positions write
            # garbage past the write point into the row's own padded
            # extent — the dense dirty-cache discipline at page
            # granularity). The sampled token only matters for rows on
            # their final chunk; the host discards the rest.
            logits, cache = self._forward_paged(
                params, chunks, cache, start, tables, lora
            )
            last = jnp.take_along_axis(
                logits, (valid - 1)[:, None, None], axis=1
            )[:, 0]
            keys = jax.random.wrap_key_data(keydata)
            tok = decode.sample_token_rows(last, greedy, t, keys, k, p)
            return tok, decode.nonfinite_rows(last), cache

        def decode_step(params, toks, cache, pos, tables, folds,
                        greedy, t, k, p, keydata, *lora):
            logits, cache = self._forward_paged(
                params, toks[:, None], cache, pos, tables, lora
            )
            last = logits[:, -1]
            keys = jax.vmap(jax.random.fold_in)(
                jax.random.wrap_key_data(keydata), folds
            )
            tok = decode.sample_token_rows(last, greedy, t, keys, k, p)
            return tok, decode.nonfinite_rows(last), cache

        def decode_spec_step(params, toks, cache, pos, tables, folds,
                             greedy, t, k, p, keydata, n_draft, *lora):
            # The paged verify window: k+1 tokens write through the
            # row's block table — committable lanes land on its private
            # tail pages (the host grew the table to cover them), lanes
            # past the table redirect to the scratch page
            # (decode._write), and the shared-prefix pages are
            # untouchable by construction (all writes land at
            # >= the row's first private position).
            return self._spec_verify(
                self._forward_paged(
                    params, toks, cache, pos, tables, lora
                ),
                toks, folds, greedy, t, k, p, keydata, n_draft,
            )

        return {
            "prefill": prefill,
            "decode_step": decode_step,
            "decode_spec_step": decode_spec_step,
        }

    def _kv_bodies(self):
        """The two kv-handoff program bodies (disaggregated serving):
        params-free page movers, generic over the cache tree so int8
        pools ship their per-token scale leaves alongside the values.
        Padded table entries are 0, so export gathers (and import
        scatters) scratch-page garbage on the unused lanes —
        garbage-by-design, exactly like a free row's decode lane."""

        def kv_export(cache, table):
            # [L, pool_pages, ...] -> [L, max_pages, ...] per leaf: one
            # row's pages in table order. NOT donated — the source pool
            # stays live until the handoff is confirmed complete.
            return {kk: vv[:, table] for kk, vv in cache.items()}

        def kv_import(pages, table, cache):
            # Scatter one exported row into this pool at the freshly
            # allocated page ids (donates the pool — in-place scatter).
            # table duplicates (the 0-padding) overlap-write only the
            # scratch page.
            return {
                kk: cache[kk].at[:, table].set(pages[kk]) for kk in cache
            }

        return {"kv_export": kv_export, "kv_import": kv_import}

    def _check_program_kind(self, kind: str) -> None:
        if kind in _KV_PROGRAM_KINDS:
            return
        super()._check_program_kind(kind)

    def program(self, kind: str):
        self._check_program_kind(kind)
        prog = self._programs.get(kind)
        if prog is not None:
            return prog
        kv = kind in _KV_PROGRAM_KINDS
        body = self._kv_bodies()[kind] if kv else self._bodies()[kind]
        ca = self.CACHE_ARGNUM.get(kind)
        donate = () if ca is None else (ca,)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        else:  # tp: head-sharded page pool, everything else replicated
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = self._cache_pspec()
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), P(), P(), cache_spec,
                    P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(), P(),
                    P(), P(), P(), P(), P(),
                ),
                "decode_spec_step": (
                    self._p_specs, P(), cache_spec, P(), P(), P(),
                    P(), P(), P(), P(), P(), P(),
                ),
                # Pages ship head-sharded exactly like the pool they
                # came from / land in: each TP shard moves its own
                # slice, no collectives (NO_COLLECTIVES-pinned in the
                # audit registry). No LoRA operands — kv programs are
                # params-free.
                "kv_export": (cache_spec, P()),
                "kv_import": (cache_spec, P(), cache_spec),
            }[kind]
            if not kv:
                specs = specs + self._lora_in_specs()
            out_specs = {
                "decode_spec_step": (P(), P(), P(), cache_spec),
                "kv_export": cache_spec,
                "kv_import": cache_spec,
            }.get(kind, (P(), P(), cache_spec))
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=out_specs,
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        self._programs[kind] = prog
        return prog

    # -- sessions ----------------------------------------------------------

    def open_session(self) -> int:
        """Open one multi-turn chat session (serving/session.py):
        returns the sid ``submit(session=)`` takes. Turn N resubmits the
        conversation-so-far and pays ~one chunk of prefill via the
        pinned prefix cache; idle sessions past the pin budget are
        evicted loudly (their next turn pays a cold prefill)."""
        return self._sessions.open()

    def close_session(self, sid: int) -> None:
        """Close a session: its pins return to ordinary LRU retention
        (the chunks may still be hit until evicted). Unknown sids raise."""
        self._sessions.close(sid)

    def _session_checkin(self, session, prompt) -> int:
        if session is None:
            return 0
        return self._sessions.check_turn(session, prompt)

    def _session_begin(self, session, rid) -> None:
        if session is not None:
            self._sessions.begin_turn(session, rid)

    def _finish(self, rid, state, tokens, reason, finished=None) -> None:
        # Every terminal state clears the session's in-flight marker (a
        # DONE turn already recorded its transcript via
        # ``_retire_session_turn``); non-session rids no-op.
        self._sessions.on_terminal(rid)
        super()._finish(rid, state, tokens, reason, finished)

    def _retire_session_turn(self, s: _PagedSlot) -> None:
        """A session turn is retiring DONE: publish its DECODE-written
        full chunks (prefill already published the prompt's — the K/V
        of a generated token is the same pure function of its prefix,
        so these are sound cache entries; MUST run before the row's
        pages release so retention sees them resident), then hand the
        tracker the new transcript + the full chain to pin."""
        toks = self._partial_tokens(s.prompt, s.generated)
        cp = self.chunk // self.page_size
        key = s.chain_key  # chain at the last prefill-published boundary
        for st in range(
            (s.prefill_len // self.chunk) * self.chunk,
            (s.pos // self.chunk) * self.chunk,
            self.chunk,
        ):
            first = st // self.page_size
            key = self.pool.register_chunk(
                toks, st, s.table[first: first + cp].tolist(),
                prev_key=key,
            )
        self._sessions.on_turn_done(
            s.session, toks, self.pool.chain_keys(toks, s.pos)
        )

    def _maybe_retire(self, row: int, finished: list[int]) -> None:
        s = self._slots[row]
        hit_eos = s.eos_id is not None and s.generated[-1] == s.eos_id
        if len(s.generated) < s.max_new and not hit_eos:
            return
        if s.session is not None:
            self._retire_session_turn(s)
        self._slots[row] = None
        self._on_slot_freed(s)
        self._finish_slot(s, DONE, "", finished)

    # -- scheduler ---------------------------------------------------------

    def _batch_headroom(self) -> bool:
        """BATCH-tier admission gate: only while at least
        ``batch_admit_free_frac`` of the pool is ALLOCATABLE (free or
        LRU-reclaimable — retired cached prefixes are headroom, not
        pressure) does throughput traffic admit — a batch backlog fills
        otherwise-idle capacity but never bids against interactive/
        standard traffic for a contended pool."""
        return (
            self.pool.allocatable_pages()
            >= self.batch_admit_free_frac * (self.pool_pages - 1)
        )

    def _admit(self, params, finished: list[int]) -> None:
        free = [i for i, s in enumerate(self._slots) if s is None]
        # The queue is sorted ONCE and the order reused across
        # admissions (queue_key is static per request, so removals keep
        # it sorted); only a preemption's requeued victim invalidates
        # it. Pool state cannot change during a candidate scan, so the
        # batch-headroom gate — an O(cached-chunks) pool walk — is
        # evaluated at most once per scan.
        ordered = None
        blocked: set[int] = set()
        while self._queue:
            # Priority-ordered admission (scheduler.queue_key):
            # interactive first (earliest deadline within the tier),
            # then standard/batch in FIFO order — an all-standard queue
            # admits exactly like the pre-tier engine. BATCH entries are
            # SKIPPED (not blocking) while the pool lacks headroom.
            if ordered is None:
                ordered = sorted(self._queue, key=self._queue_key)
            req = None
            headroom = None
            for cand in ordered:
                if cand.rid in blocked:
                    continue
                if cand.tier == TIER_RANK[BATCH]:
                    if headroom is None:
                        headroom = self._batch_headroom()
                    if not headroom:
                        continue
                req = cand
                break
            if req is None:
                break
            if not free:
                # No free slot: an INTERACTIVE arrival may preempt a
                # strictly-lower-priority active row for its slot (and
                # pages); everyone else waits for a retirement.
                n0 = len(self._queue)
                row = self._preempt_lower_priority(req.tier, finished)
                if len(self._queue) != n0:
                    ordered = None
                if row is None:
                    break
                free.append(row)
            slot = self._try_allocate(req)
            while slot is None:
                # Page shortage: idle-session pins break FIRST (cheap —
                # the session just loses retention), then strictly-
                # lower-priority actives are preempted for their pages.
                # BATCH never breaks a pin: pinned pages are not the
                # idle capacity batch is allowed to fill (the router
                # scores them unavailable for the same reason) — a
                # batch row this large waits for ordinary retirements.
                if (
                    req.tier != TIER_RANK[BATCH]
                    and self._sessions.evict_idle()
                ):
                    slot = self._try_allocate(req)
                    continue
                n0 = len(self._queue)
                row = self._preempt_lower_priority(req.tier, finished)
                if len(self._queue) != n0:
                    ordered = None
                if row is None:
                    break
                free.append(row)
                slot = self._try_allocate(req)
            if slot is None:
                # Highest-priority admissible entry waits for pages
                # (deferral, not a hang): decode keeps running and
                # retirements free pages. With NO live rows nothing can
                # ever retire — a head this large would stall the queue
                # for good when the pages it needs are pinned by
                # sessions whose own queued turns (the only thing that
                # releases the pins) sit right behind it — so only then
                # do later, smaller entries go around it this tick.
                if any(s is not None for s in self._slots):
                    break
                blocked.add(req.rid)
                continue
            self._queue.remove(req)
            if ordered is not None:
                ordered.remove(req)
            row = free.pop(0)
            self._slots[row] = slot
            log_event(
                "admit", rid=slot.rid, row=row,
                cached_tokens=slot.pos or None,
                resume_prefix=slot.resume_base or None,
                priority=(
                    TIER_NAME[slot.tier]
                    if slot.tier != TIER_RANK[STANDARD] else None
                ),
                session=slot.session,
                t=round(self._clock(), 6),
            )
        self._chunk_prefill_tick(params, finished)

    def _preempt_lower_priority(self, tier: int, finished) -> int | None:
        """Preempt the lowest-priority-then-youngest active row whose
        tier is STRICTLY below ``tier`` (admission-side preemption: an
        interactive arrival takes a batch row's slot/pages regardless of
        age; standard/batch arrivals never preempt — they wait for a
        retirement, exactly the pre-tier schedule). Returns the freed
        row index, or None when the arrival may not preempt or nothing
        outranked exists."""
        if tier != TIER_RANK[INTERACTIVE]:
            return None
        cands = [
            (preemption_key(s.tier, s.rid), i)
            for i, s in enumerate(self._slots)
            if s is not None and s.tier > tier
        ]
        if not cands:
            return None
        (_, rid), row = max(cands)
        s = self._slots[row]
        self._slots[row] = None
        self._on_slot_freed(s)
        self.counters["preempt_priority"] += 1
        log_event(
            "preempt_priority", rid=rid, row=row, depth=s.pos,
            victim_tier=TIER_NAME[s.tier], for_tier=TIER_NAME[tier],
            t=round(self._clock(), 6),
        )
        self._requeue([self._pending_from_slot(s, bump=False)])
        return row

    def _try_allocate(self, req: _Pending) -> _PagedSlot | None:
        """Build a slot for ``req`` if the pool can cover its prefill
        extent: shared prefix pages are acquired from the prefix cache
        (never for a quarantine retry — a poisoned row re-prefills from
        scratch on purpose), private pages allocated for the rest,
        rounded up to the chunk the padded final prefill writes."""
        prefix = self._partial_tokens(req.prompt, req.gen)
        plen = prefix.shape[0]
        if req.nan_retried:
            cached, shared, chain_key = 0, [], ""
        else:
            cached, shared, chain_key = self.pool.match_prefix(
                prefix, plen - 1
            )
        ext = -(-plen // self.chunk) * self.chunk  # padded prefill extent
        fresh = self.pool.alloc(ext // self.page_size - len(shared))
        if fresh is None:
            # Deferred, not admitted: the match never happened as far as
            # the hit counters are concerned — a head-of-line request
            # retrying every tick must not inflate the committed stats.
            # (A quarantine retry never queried, so nothing to cancel.)
            if not req.nan_retried:
                self.pool.cancel_match(cached, shared)
            return None
        if cached:
            log_event(
                "prefix_hit", rid=req.rid, cached_tokens=cached,
                prompt_len=plen, t=round(self._clock(), 6),
                quant=self.kv_quant if self.kv_quant != "none" else None,
            )
        pids = list(shared) + fresh
        table = np.zeros((self.max_pages,), np.int32)
        table[: len(pids)] = pids
        if req.session is not None:
            # First admission of a session turn commits its prefix-hit
            # economics (preemption re-admissions are de-duplicated by
            # rid inside the tracker).
            self._sessions.note_admit(req.rid, cached, req.resub_len)
        return _PagedSlot(
            rid=req.rid, prompt=req.prompt, max_new=req.max_new,
            eos_id=req.eos_id, pos=cached, fold=len(req.gen),
            generated=list(req.gen), greedy=req.greedy,
            t=req.t, k=req.k, p=req.p, keydata=req.keydata,
            deadline=req.deadline, retries=req.retries,
            nan_retried=req.nan_retried,
            tier=req.tier, session=req.session,
            resub_len=req.resub_len, tenant_slot=req.tenant_slot,
            prefix=prefix, prefill_len=plen, table=table, pids=pids,
            n_pages=len(pids), prefill_keydata=req.prefill_keydata,
            resume_base=len(req.gen), chain_key=chain_key,
        )

    def _chunk_prefill_tick(self, params, finished: list[int]) -> None:
        """Advance every mid-prefill row by ONE chunk (one grouped
        dispatch): long prompts trickle in across ticks while decode-
        ready neighbours keep generating — the chunked-prefill
        contract."""
        rows = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None and not s.ready
        ]
        if rows and any(
            s is not None and s.ready
            and s.tier == TIER_RANK[INTERACTIVE]
            for s in self._slots
        ):
            # BATCH prefill yields to interactive decode (the prefill
            # half of the decode-tick yield): while a latency-tier row
            # is generating, throughput rows do not inflate its ticks
            # with their chunk prefills. Deliberately NOT while the
            # interactive row is still mid-prefill: batch prefill
            # proceeding there keeps its pages held, which is what the
            # preempt-lowest path reclaims the moment the latency row
            # needs them. Bounded: interactive rows retire within
            # max_new ticks, then the backlog streams in. Standard rows
            # are untouched (the all-STANDARD schedule stays the
            # pre-tier one).
            rows = [
                (i, s) for i, s in rows
                if s.tier != TIER_RANK[BATCH]
            ]
        if not rows:
            return
        n = len(rows)
        npad = next(g for g in self._groups if g >= n)
        idx = list(range(n)) + [0] * (npad - n)
        chunks = np.zeros((npad, self.chunk), np.int32)
        valid = np.ones((npad,), np.int32)
        start = np.zeros((npad,), np.int32)
        tables = np.zeros((npad, self.max_pages), np.int32)
        greedy = np.zeros((npad,), np.bool_)
        t = np.ones((npad,), np.float32)
        k = np.full((npad,), self.cfg.vocab_size, np.int32)
        p = np.full((npad,), 2.0, np.float32)
        keydata = np.zeros((npad, self._key_words), np.uint32)
        tenants = np.zeros((npad,), np.int32)
        for j, ii in enumerate(idx):
            _, s = rows[ii]
            v = min(self.chunk, s.prefill_len - s.pos)
            chunks[j, :v] = s.prefix[s.pos : s.pos + v]
            valid[j] = v
            start[j] = s.pos
            tables[j] = s.table
            greedy[j] = s.greedy
            t[j], k[j], p[j] = s.t, s.k, s.p
            keydata[j] = s.prefill_keydata
            tenants[j] = s.tenant_slot
        res = self._dispatch(
            "prefill", params, [], finished,
            jnp.asarray(chunks), jnp.asarray(valid), jnp.asarray(start),
            jnp.asarray(tables), None, jnp.asarray(greedy),
            jnp.asarray(t), jnp.asarray(k), jnp.asarray(p),
            jnp.asarray(keydata),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return  # recovery converted every in-flight row already
        toks, bad = res
        for j in range(n):
            row, s = rows[j]
            if bad[j]:
                self._slots[row] = None
                self._on_slot_freed(s)
                self._quarantine_slot(s, row, finished, phase="prefill")
                continue
            v = min(self.chunk, s.prefill_len - s.pos)
            if v == self.chunk:
                # A full chunk lies entirely inside the prefix: publish
                # its pages for prefix sharing (clean chunks only — a
                # flagged row never contaminates the cache). The chain
                # key rides the slot, so each publish is one digest.
                cp = self.chunk // self.page_size
                first = s.pos // self.page_size
                s.chain_key = self.pool.register_chunk(
                    s.prefix, s.pos,
                    s.table[first : first + cp].tolist(),
                    prev_key=s.chain_key,
                )
            s.pos += v
            if s.pos >= s.prefill_len:
                s.generated.append(int(toks[j]))
                if self.role == "prefill":
                    # The row is now handoff-eligible: it parks here
                    # (pages held) until the router pumps it to a decode
                    # worker. bytes = the pages a handoff will ship.
                    log_event(
                        "prefill_done", rid=s.rid,
                        prompt_len=s.prefill_len, pages=s.n_pages,
                        bytes=(
                            s.n_pages * self.page_size
                            * self._bytes_per_position()
                        ),
                        t=round(self._clock(), 6),
                    )
                self._maybe_retire(row, finished)

    def _grow_for_drafts(self, s: _PagedSlot, n: int) -> int:
        """Best-effort block-table growth covering the row's draft
        window (committable positions pos..pos+n need REAL pages — an
        accepted draft's K/V becomes the row's cache). Returns how many
        drafts are actually covered. Never preempts a live row and
        never breaks a session pin: drafts are an optimisation, so page
        pressure just shrinks the window (the verify step still commits
        its one guaranteed token on the already-covered page; lanes
        past the shrunk window ride table-zero lanes onto the scratch
        page). This is also why speculative width does not change the
        router's page-pressure accounting: at most these few
        transiently-held tail pages per row, already counted by
        ``pages_in_use`` like any other allocation."""
        while s.n_pages * self.page_size <= s.pos + n:
            got = self.pool.alloc(1)
            if got is None:
                n = s.n_pages * self.page_size - s.pos - 1
                break
            s.table[s.n_pages] = got[0]
            s.pids += got
            s.n_pages += 1
        return max(0, n)

    def _decode_tick_spec(self, params, finished: list[int]) -> None:
        """The paged speculative tick: the dense ``_decode_tick_spec``
        plus block tables, the tier-yield schedule, and draft-window
        page growth. Rollback is depth truncation: a rejected draft's
        K/V stays as garbage past the row's committed ``pos`` on the
        row's PRIVATE tail page — the prefix cache and any session-
        pinned pages never see speculative state."""
        interactive_live = any(
            s is not None and s.tier == TIER_RANK[INTERACTIVE]
            for s in self._slots
        )
        self._ensure_decode_pages(finished, skip_batch=interactive_live)
        ready = []
        yielded = False
        for i, s in enumerate(self._slots):
            if s is None or not s.ready:
                continue
            if interactive_live and s.tier == TIER_RANK[BATCH]:
                yielded = True
                continue
            ready.append((i, s))
        if yielded:
            self.counters["batch_yield_ticks"] += 1
        if not ready:
            return
        b, width = self.slots, self.speculative_k + 1
        toks = np.zeros((b, width), np.int32)
        n_draft = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.max_pages), np.int32)
        folds = np.zeros((b,), np.int32)
        greedy = np.ones((b,), np.bool_)
        t = np.ones((b,), np.float32)
        k = np.full((b,), self.cfg.vocab_size, np.int32)
        p = np.full((b,), 2.0, np.float32)
        keydata = np.zeros((b, self._key_words), np.uint32)
        tenants = np.zeros((b,), np.int32)
        for i, s in ready:
            drafts = self._draft_tokens(s)
            drafts = drafts[: self._grow_for_drafts(s, len(drafts))]
            toks[i, 0] = s.generated[-1]
            toks[i, 1 : 1 + len(drafts)] = drafts
            n_draft[i] = len(drafts)
            pos[i] = s.pos
            tables[i] = s.table
            folds[i] = s.fold
            greedy[i] = s.greedy
            t[i], k[i], p[i] = s.t, s.k, s.p
            keydata[i] = s.keydata
            tenants[i] = s.tenant_slot
        res = self._dispatch(
            "decode_spec_step", params, None, finished,
            jnp.asarray(toks), None, jnp.asarray(pos),
            jnp.asarray(tables), jnp.asarray(folds),
            jnp.asarray(greedy), jnp.asarray(t), jnp.asarray(k),
            jnp.asarray(p), jnp.asarray(keydata), jnp.asarray(n_draft),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return
        out, n_acc, bad = res
        for i, s in ready:
            if bad[i]:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._quarantine_slot(s, i, finished)
                continue
            self._commit_spec(
                i, s, out[i], int(n_acc[i]), int(n_draft[i]), finished
            )

    def _decode_tick(self, params, finished: list[int]) -> None:
        if self.role == "prefill":
            # A PREFILL worker never decodes: finished-prefill rows park
            # (ready, pages held) until the router's handoff pump ships
            # them to a decode worker (``export_handoff``). _maybe_retire
            # already retired any max_new==1 row at its final chunk.
            return
        if self.speculative_k:
            return self._decode_tick_spec(params, finished)
        # BATCH decode yields to a live interactive row (the decode
        # half of the chunk-prefill yield below): while a latency-tier
        # request occupies a slot, throughput rows sit out the tick —
        # their lanes stay zeroed (table 0 -> the scratch page), so the
        # interactive tick's working set shrinks to the latency rows'
        # own pages instead of streaming every batch row's cache
        # through it. A skipped tick recomputes nothing (the row's
        # operands are a pure function of its own state), so batch
        # tokens stay bit-equal — just later. Bounded: interactive
        # rows retire within max_new ticks, then batch streams again.
        # STANDARD rows never yield (the all-STANDARD schedule is the
        # pre-tier one).
        interactive_live = any(
            s is not None and s.tier == TIER_RANK[INTERACTIVE]
            for s in self._slots
        )
        self._ensure_decode_pages(finished, skip_batch=interactive_live)
        ready = []
        yielded = False
        for i, s in enumerate(self._slots):
            if s is None or not s.ready:
                continue
            if interactive_live and s.tier == TIER_RANK[BATCH]:
                yielded = True
                continue
            ready.append((i, s))
        if yielded:
            self.counters["batch_yield_ticks"] += 1
        if not ready:
            return
        b = self.slots
        toks = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.max_pages), np.int32)
        folds = np.zeros((b,), np.int32)
        greedy = np.ones((b,), np.bool_)
        t = np.ones((b,), np.float32)
        k = np.full((b,), self.cfg.vocab_size, np.int32)
        p = np.full((b,), 2.0, np.float32)
        keydata = np.zeros((b, self._key_words), np.uint32)
        tenants = np.zeros((b,), np.int32)
        for i, s in ready:
            # Free AND mid-prefill rows stay all-zero: table 0 -> the
            # scratch page, so their garbage write/read never touches a
            # live row's pages (and slot 0 is the zero adapter).
            toks[i] = s.generated[-1]
            pos[i] = s.pos
            tables[i] = s.table
            folds[i] = s.fold
            greedy[i] = s.greedy
            t[i], k[i], p[i] = s.t, s.k, s.p
            keydata[i] = s.keydata
            tenants[i] = s.tenant_slot
        res = self._dispatch(
            "decode_step", params, None, finished, jnp.asarray(toks),
            None, jnp.asarray(pos), jnp.asarray(tables),
            jnp.asarray(folds), jnp.asarray(greedy), jnp.asarray(t),
            jnp.asarray(k), jnp.asarray(p), jnp.asarray(keydata),
            *self._lora_dispatch_args(tenants),
        )
        if res is None:
            return
        out, bad = res
        for i, s in ready:
            if bad[i]:
                self._slots[i] = None
                self._on_slot_freed(s)
                self._quarantine_slot(s, i, finished)
                continue
            s.generated.append(int(out[i]))
            s.pos += 1
            s.fold += 1
            self._maybe_retire(i, finished)

    def _ensure_decode_pages(
        self, finished: list[int], skip_batch: bool = False
    ) -> None:
        """Grow each decode-ready row's table to cover its next write.
        Pool exhaustion preempts the YOUNGEST other active request
        (admitted last -> preempted first): its clean prefix requeues as
        a resume entry — no retry charge, no token loss — and its pages
        come back to the pool. ``skip_batch``: batch rows yielding this
        tick don't advance, so growing their tables now could only fire
        a needless preemption under pressure."""
        for i in range(self.slots):
            # Read the LIVE slot list each iteration: a preemption fired
            # for an earlier row may have freed this one, and growing a
            # dead slot would leak its page (and could preempt a live
            # row to feed a corpse).
            s = self._slots[i]
            if s is None or not s.ready:
                continue
            if skip_batch and s.tier == TIER_RANK[BATCH]:
                continue
            if s.pos // self.page_size < s.n_pages:
                continue
            while True:
                got = self.pool.alloc(1)
                if got is not None:
                    s.table[s.n_pages] = got[0]
                    s.pids += got
                    s.n_pages += 1
                    break
                # Retention must never deadlock allocation: idle-session
                # pins break (loudly) before any live row is preempted.
                if self._sessions.evict_idle():
                    continue
                others = [
                    o.tier for o in self._slots
                    if o is not None and o.rid != s.rid
                ]
                if others and max(others) < s.tier:
                    # Every neighbour strictly outranks this row: IT is
                    # the lowest-priority occupant, so it yields its own
                    # pages (a batch row must never evict interactive
                    # state to keep growing) — clean resume entry, like
                    # any other preemption.
                    self._preempt_row(i)
                    break
                if not self._preempt_one(exclude_rid=s.rid, finished=finished):
                    from pytorch_distributed_tpu.serving.lifecycle import (
                        PagePoolExhausted,
                    )

                    raise PagePoolExhausted(
                        f"no KV page available for rid {s.rid} at depth "
                        f"{s.pos} and nothing left to preempt — "
                        f"pool_pages={self.pool_pages} cannot hold one "
                        "row this deep (construction should have "
                        "rejected this configuration)"
                    )

    def _preempt_one(self, *, exclude_rid: int, finished) -> bool:
        # Preempt-lowest-priority-then-youngest (scheduler.py): the
        # victim is the active row with the MAX (tier rank, rid) — a
        # batch row goes before an interactive row regardless of age,
        # and an all-STANDARD batch recovers PR-8's preempt-youngest
        # exactly.
        cands = [
            (preemption_key(s.tier, s.rid), i)
            for i, s in enumerate(self._slots)
            if s is not None and s.rid != exclude_rid
        ]
        if not cands:
            return False
        self._preempt_row(max(cands)[1])
        return True

    def _preempt_row(self, row: int) -> None:
        """Convert one active row to a clean resume entry (no retry
        charge, pages released) — the shared tail of every preemption
        path."""
        s = self._slots[row]
        self._slots[row] = None
        self._on_slot_freed(s)
        self.counters["preemptions"] += 1
        log_event(
            "preempt", rid=s.rid, row=row, depth=s.pos,
            generated=len(s.generated) - s.resume_base,
            tier=(
                TIER_NAME[s.tier]
                if s.tier != TIER_RANK[STANDARD] else None
            ),
            t=round(self._clock(), 6),
        )
        self._requeue([self._pending_from_slot(s, bump=False)])

    def _on_slot_freed(self, s: _Slot) -> None:
        self.pool.release(s.pids)
        s.pids = []

    def _recover_dispatch_failure(self, kind, err, group_pendings,
                                  finished) -> None:
        # The donated page pool was consumed with the dispatch: its
        # content is gone, so every cached prefix chunk would alias
        # garbage. Reset the pool BEFORE base recovery (which may raise
        # DispatchFailure at the end) and zero the slots' page lists so
        # the freed-slot hook has nothing stale to release.
        for s in self._slots:
            if s is not None:
                s.pids = []
        self.pool.reset()
        # Every pinned chunk's content died with the pool: drop the
        # pins (transcripts survive — the next turn re-pays prefill).
        self._sessions.on_pool_reset()
        super()._recover_dispatch_failure(
            kind, err, group_pendings, finished
        )

    # -- introspection / warmup --------------------------------------------

    def warmup(self, params) -> int:
        """Compile every prefill group shape plus the decode step (the
        whole steady-state compile set: chunked prefill has ONE token
        shape, so there is no bucket dimension to cover). Disaggregated
        roles additionally warm their side of the kv-handoff pair —
        export on PREFILL workers, import on DECODE workers — so a
        steady-state handoff compiles nothing."""
        if self.has_work():
            raise RuntimeError("warmup requires an idle engine")
        params = self._place_params(params)
        for g in self._groups:
            args = self.example_args(
                "prefill", params, group=g, cache=self._take_cache()
            )
            _, _, cache = self.program("prefill")(*args)
            self._cache = cache
        self._rewarm_first_prefill(params)
        step_kind = self._program_kinds()[-1]
        args = self.example_args(
            step_kind, params, cache=self._take_cache()
        )
        *_, cache = self.program(step_kind)(*args)
        self._cache = cache
        if self.role == "prefill":
            cache, table = self.example_args(
                "kv_export", params, cache=self._take_cache()
            )
            jax.block_until_ready(self.program("kv_export")(cache, table))
            self._cache = cache  # export does not donate
        elif self.role == "decode":
            # Twice, threading the output back in: the first call's
            # donated pool is a decode_step OUTPUT, but every steady
            # import consumes a previous import's output — whose layout
            # can hash differently (the _rewarm_first_prefill trick for
            # the handoff path; pinned by the disagg compile tests).
            for _ in range(2):
                pages, table, cache = self.example_args(
                    "kv_import", params, cache=self._take_cache()
                )
                self._cache = self.program("kv_import")(
                    self._place_handoff_pages(pages), table, cache
                )
            # The first decode tick after an import consumes the
            # import's output pool — cover THAT input layout too.
            args = self.example_args(
                step_kind, params, cache=self._take_cache()
            )
            *_, cache = self.program(step_kind)(*args)
            self._cache = cache
        return self.compile_count()

    def example_args(self, kind: str, params, *, bucket: int | None = None,
                     group: int = 1, cache: decode.Cache | None = None):
        """Example argument tuple for lowering/auditing ``kind``.
        ``bucket`` is accepted for API parity with the dense engine and
        ignored — the chunk is the only prefill token shape."""
        if cache is None:
            cache = self._new_cache()
        mp = self.max_pages
        if kind == "prefill":
            npad = next(g for g in self._groups if g >= group)
            return (
                params,
                jnp.zeros((npad, self.chunk), jnp.int32),
                jnp.ones((npad,), jnp.int32),
                jnp.zeros((npad,), jnp.int32),
                jnp.zeros((npad, mp), jnp.int32),
                cache,
                jnp.ones((npad,), jnp.bool_),
                jnp.ones((npad,), jnp.float32),
                jnp.full((npad,), self.cfg.vocab_size, jnp.int32),
                jnp.full((npad,), 2.0, jnp.float32),
                jnp.zeros((npad, self._key_words), jnp.uint32),
            ) + self._lora_dispatch_args(np.zeros((npad,), np.int32))
        if kind == "decode_step":
            b = self.slots
            return (
                params,
                jnp.zeros((b,), jnp.int32),
                cache,
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, mp), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), self.cfg.vocab_size, jnp.int32),
                jnp.full((b,), 2.0, jnp.float32),
                jnp.zeros((b, self._key_words), jnp.uint32),
            ) + self._lora_dispatch_args(np.zeros((b,), np.int32))
        if kind == "decode_spec_step":
            b, width = self.slots, self.speculative_k + 1
            return (
                params,
                jnp.zeros((b, width), jnp.int32),
                cache,
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, mp), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), self.cfg.vocab_size, jnp.int32),
                jnp.full((b,), 2.0, jnp.float32),
                jnp.zeros((b, self._key_words), jnp.uint32),
                jnp.zeros((b,), jnp.int32),
            ) + self._lora_dispatch_args(np.zeros((b,), np.int32))
        if kind == "kv_export":
            # kv programs are params-free: ``params`` is accepted (and
            # ignored) for signature parity with every other kind.
            return (cache, jnp.zeros((mp,), jnp.int32))
        if kind == "kv_import":
            pages = {
                kk: jnp.zeros(
                    (vv.shape[0], mp) + tuple(vv.shape[2:]), vv.dtype
                )
                for kk, vv in cache.items()
            }
            return (pages, jnp.zeros((mp,), jnp.int32), cache)
        raise KeyError(f"unknown batched program kind {kind!r}")

    # -- disaggregation: kv handoff ----------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> int:
        if self.role == "decode":
            raise ValueError(
                "this engine is a DECODE worker: it accepts rows only "
                "via import_handoff (finished prefills) or adopt "
                "(failover resume entries) — route fresh prompts to a "
                "prefill or colocated worker"
            )
        return super().submit(prompt, max_new_tokens, **kw)

    def handoff_ready(self) -> list[int]:
        """Engine rids of rows parked on this PREFILL worker with their
        prefill finished — the rows ``export_handoff`` can ship. Empty
        on every other role (colocated rows decode in place)."""
        if self.role != "prefill":
            return []
        return [
            s.rid for s in self._slots
            if s is not None and s.ready
        ]

    def export_handoff(self, rid: int) -> KVHandoff:
        """Gather one parked row's KV pages off the pool (kv_export —
        warmed, zero steady-state compiles) and package everything a
        decode worker needs to continue it bit-identically. READ-ONLY:
        the row stays live (pages held, fault model intact) until
        ``complete_handoff`` confirms the import landed — a destination
        dying mid-handoff costs nothing but the gather."""
        s = next(
            (x for x in self._slots if x is not None and x.rid == rid),
            None,
        )
        if s is None:
            raise KeyError(f"no active row with rid {rid} to hand off")
        if not s.ready:
            raise ValueError(
                f"rid {rid} is mid-prefill (pos {s.pos} < "
                f"{s.prefill_len}) — only finished prefills hand off"
            )
        t0 = time.perf_counter()
        cache = self._take_cache()
        pages = self.program("kv_export")(cache, jnp.asarray(s.table))
        self._cache = cache  # not donated: the pool buffer stays valid
        jax.block_until_ready(pages)
        export_s = time.perf_counter() - t0
        wire = sum(
            v.size * v.dtype.itemsize for v in jax.tree.leaves(pages)
        )
        return KVHandoff(
            entry=self._pending_from_slot(s, bump=False),
            pages=pages, n_pages=s.n_pages, pos=s.pos, fold=s.fold,
            generated=list(s.generated), prefill_len=s.prefill_len,
            resume_base=s.resume_base, page_size=self.page_size,
            max_pages=self.max_pages, kv_quant=self.kv_quant,
            src_rid=s.rid,
            useful_bytes=(
                s.n_pages * self.page_size * self._bytes_per_position()
            ),
            wire_bytes=int(wire), export_s=export_s,
        )

    def complete_handoff(self, rid: int) -> None:
        """The destination confirmed the import: release the source
        row WITHOUT a terminal result — ownership (and the client's
        rid mapping, which the router owns) moved to the destination
        engine. The freed pages go back to this worker's pool."""
        for i, s in enumerate(self._slots):
            if s is not None and s.rid == rid:
                self._slots[i] = None
                self._on_slot_freed(s)
                self.pool.note_handoff_out(s.n_pages)
                self.counters["handoffs_out"] += 1
                return
        raise KeyError(f"no active row with rid {rid} to complete")

    def can_import_handoff(self, h: KVHandoff) -> bool:
        """Cheap host-side gate the router's handoff pump scores
        targets with: a free slot row plus allocatable pool headroom
        for the row's pages (LRU-evictable cached prefixes count —
        they are reclaimable, not pressure)."""
        return (
            self.role != "prefill"
            and any(s is None for s in self._slots)
            and self.pool.allocatable_pages() >= h.n_pages
            and h.page_size == self.page_size
            and h.max_pages == self.max_pages
            and h.kv_quant == self.kv_quant
        )

    def _place_handoff_pages(self, pages):
        """Commit an exported pages tree to THIS engine's placement:
        the wire hop of the handoff. The source committed the tree to
        ITS device(s); re-committing keeps every kv_import operand on
        one placement (and keeps the import's compiled signature
        identical to the one ``warmup`` built — a sharding-hash
        mismatch here would be a steady-state compile)."""
        if self.mode == "tp":
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = jax.tree.map(
                lambda sp: NamedSharding(self._mesh, sp),
                self._cache_pspec(),
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.device_put(pages, sharding)
        dev = self.device if self.device is not None else jax.devices()[0]
        return jax.device_put(pages, dev)

    def import_handoff(
        self, h: KVHandoff, finished: list[int] | None = None
    ) -> int | None:
        """Land one exported row in this worker's pool (kv_import —
        donated in-place scatter, warmed on DECODE workers) and seat it
        as a decode-ready slot under a fresh local rid. Returns the new
        rid, or None when the import could not land (no headroom, or
        the scatter dispatch failed and was RECOVERED — pool reset,
        in-flight rows converted to resume entries exactly like any
        failed dispatch; terminal rids from that recovery land in
        ``finished``). The source row is untouched either way until
        ``complete_handoff``."""
        if self.role == "prefill":
            raise ValueError(
                "a PREFILL worker cannot import handoffs — it only "
                "exports them"
            )
        if (
            h.page_size != self.page_size
            or h.max_pages != self.max_pages
            or h.kv_quant != self.kv_quant
        ):
            raise ValueError(
                "kv_handoff geometry mismatch: source pages are "
                f"(page_size={h.page_size}, max_pages={h.max_pages}, "
                f"kv_quant={h.kv_quant!r}) but this engine is "
                f"(page_size={self.page_size}, max_pages="
                f"{self.max_pages}, kv_quant={self.kv_quant!r}) — "
                "disaggregated fleets must share the page geometry"
            )
        q = h.entry
        if len(q.prompt) + q.max_new > self.max_len:
            raise ValueError(
                f"handed-off entry needs {len(q.prompt) + q.max_new} "
                f"cache positions but this engine's max_len is "
                f"{self.max_len}"
            )
        row = next(
            (i for i, s in enumerate(self._slots) if s is None), None
        )
        if row is None:
            return None
        pids = self.pool.alloc_for_handoff(h.n_pages)
        if pids is None:
            return None
        table = np.zeros((self.max_pages,), np.int32)
        table[: h.n_pages] = pids
        pages = self._place_handoff_pages(h.pages)
        try:
            cache = self.program("kv_import")(
                pages, jnp.asarray(table), self._take_cache()
            )
        except Exception as err:
            # The donated pool was consumed with the failed scatter:
            # same recovery as any failed dispatch (pool reset, rows to
            # resume entries). May raise DispatchFailure past the
            # streak budget — the router treats that as replica death.
            self.pool.release(pids)
            self._recover_dispatch_failure(
                "kv_import", err, [],
                finished if finished is not None else [],
            )
            return None
        self._cache = cache
        rid = self._next_rid
        self._next_rid += 1
        self._slots[row] = _PagedSlot(
            rid=rid, prompt=q.prompt, max_new=q.max_new, eos_id=q.eos_id,
            pos=h.pos, fold=h.fold, generated=list(h.generated),
            greedy=q.greedy, t=q.t, k=q.k, p=q.p, keydata=q.keydata,
            deadline=q.deadline, retries=q.retries,
            nan_retried=q.nan_retried, tier=q.tier,
            # Sessions are engine-local (pinned pages live on the
            # source); a handed-off turn finishes as a plain request,
            # exactly like adopt().
            session=None, resub_len=0, tenant_slot=q.tenant_slot,
            prefix=self._partial_tokens(
                q.prompt, list(q.gen)[: h.resume_base]
            ),
            prefill_len=h.prefill_len, table=table, pids=list(pids),
            n_pages=h.n_pages, prefill_keydata=q.prefill_keydata,
            resume_base=h.resume_base, chain_key="",
        )
        self.counters["handoffs_in"] += 1
        return rid


@functools.lru_cache(maxsize=None)
def shim_engine(
    cfg: ModelConfig, max_len: int, mesh_cfg: MeshConfig | None = None
) -> DecodeEngine:
    """Engine cache backing the models/decode.generate* compat shims:
    exact-length buckets (identical compile behaviour to the old
    monolithic entry — one prefill compile per distinct prompt length)
    and one engine per (cfg, max_len, mesh). Cache pooling is OFF so a
    shim call frees its cache like the old jit-internal path did — these
    engines live forever in this lru_cache, and a pooled cache per
    distinct (max_len, batch) would grow device memory with request
    diversity. Real serving loops should construct a DecodeEngine
    directly with a fixed max_len and power-of-two buckets (pooling on)."""
    return DecodeEngine(
        cfg, max_len=max_len, mesh_cfg=mesh_cfg, pool_caches=False
    )
