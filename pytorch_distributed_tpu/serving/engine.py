"""Persistent donated-KV decode engine: the serving fast path.

The monolithic ``generate`` programs (models/decode.py) are the wrong
shape for a serving loop: the KV cache is jit-internal (re-allocated and
re-zeroed every request), every distinct prompt length compiles a fresh
prefill+loop program, and — before this PR — every sampling config change
recompiled too. ``DecodeEngine`` restructures generation into two
long-lived compiled programs, the shape TPU serving practice settles on
(Fine-Tuning and Serving Gemma on Cloud TPU; the pjit-scaling playbook —
PAPERS.md):

- ``prefill(params, prompt, prompt_len, cache, t, k, p, key)``
  runs the whole (bucket-padded) prompt and samples the first token;
- ``decode_run(params, tok, cache, pos, n, t, k, p, key)``
  runs n single-token steps in one dispatch (a fori_loop with a TRACED
  trip count — one compile covers every generation length);
- ``decode_step(...)`` is the single-step form behind ``stream()``.

Three levers, all machine-checked:

1. **Buffer donation**: the cache is ``donate_argnums``-donated through
   every program, and the engine keeps the returned buffer in a pool —
   steady-state serving allocates and zero-fills NOTHING per request.
   Reusing a dirty buffer is sound because decode's cache discipline
   (models/decode.py) masks key positions > pos and overwrites each row
   before it becomes readable; tests/test_serving.py pins it, including
   the GQA edge. Donation is verified to actually alias in the compiled
   executable (``verify_donation`` + the strict mode of
   analysis/audit.check_donation) — a silently-rejected alias would
   double-buffer the largest tensor in the server.
2. **Bounded compilation**: prompts are padded to a small set of
   ``BucketSpec`` lengths (default powers of two), so steady-state
   serving compiles O(buckets) prefill programs + ONE decode program —
   not O(requests). Sampling params are traced scalars
   (decode.sampling_scalars); only greedy-vs-sampled is static.
3. **Comm/compute overlap (ZeRO-3 mode)**: decode from full-shard
   training layouts routes the layer scan through
   ops/layer_scan.scan_layers's windowed double-buffer schedule
   (``MeshConfig.prefetch_buffers``), so layer l+1's param all-gathers
   stream in under layer l's compute — the decode-side twin of the
   explicit training path's prefetch (closes ROADMAP PR-3 follow-up (c)).

Modes (one engine per mode x config):
- plain: single device, whole params.
- tp (``mesh_cfg.tensor`` > 1): shard_map over a "tensor" mesh, Megatron
  layouts, local-head cache shards (the cache pytree is a GLOBAL array
  sharded over the head dim — 1/tp of the cache HBM per chip).
- zero3 (``mesh_cfg.fsdp`` > 1, full_shard): auto-partitioned decode in
  the ZeRO-3 training layout with the windowed gather schedule above.

Outputs are bit-equal to the monolithic reference paths for identical
requests (greedy and fixed-key sampled) — same forward, same sampler,
same key-folding schedule; padded prompt rows and pooled-buffer garbage
are masked out of every reduction. Pinned by tests/test_serving.py.

Not thread-safe: the cache pool hands the SAME buffer to concurrent
requests of one batch size. Serialise requests per engine (or shard
engines per worker).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig
from pytorch_distributed_tpu.models import decode

_PROGRAM_KINDS = ("prefill", "decode_run", "decode_step")


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Prompt-length buckets. A request of length T compiles (at most)
    the program of the smallest bucket >= T; ``()`` means exact-length
    (no padding — one compile per distinct length, the compat-shim
    behaviour)."""

    buckets: tuple[int, ...] = ()

    def __post_init__(self) -> None:
        b = tuple(self.buckets)
        if any(x <= 0 for x in b) or list(b) != sorted(set(b)):
            raise ValueError(
                f"buckets must be strictly increasing positives, got {b}"
            )
        object.__setattr__(self, "buckets", b)

    @classmethod
    def powers_of_two(
        cls, max_len: int, min_bucket: int = 128
    ) -> "BucketSpec":
        """128/256/.../max_len (first bucket = min_bucket clipped to
        max_len; max_len itself is always the last bucket so every
        admissible prompt has a home)."""
        if min_bucket <= 0 or max_len <= 0:
            raise ValueError("min_bucket and max_len must be positive")
        out = []
        b = min_bucket
        while b < max_len:
            out.append(b)
            b *= 2
        out.append(max_len)
        return cls(tuple(out))

    def bucket_for(self, length: int) -> int:
        if not self.buckets:
            return length
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )


class DecodeEngine:
    """See module docstring. Construct once per (cfg, max_len, bucket
    spec, mesh); call ``generate`` / ``stream`` per request with any
    params matching ``cfg`` (params are call arguments, not engine state,
    so one engine serves many checkpoints of one architecture)."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_len: int,
        buckets: BucketSpec | None = None,
        mesh_cfg: MeshConfig | None = None,
        pool_caches: bool = True,
    ) -> None:
        if max_len > cfg.n_ctx:
            raise ValueError(
                f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}"
            )
        self.cfg = cfg
        self.max_len = int(max_len)
        self.buckets = buckets or BucketSpec()
        if self.buckets.buckets and self.buckets.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket {self.buckets.buckets[-1]} exceeds "
                f"max_len {max_len}"
            )
        self.mesh_cfg = mesh_cfg
        self._n_kv = None
        self._prefetch_buffers = 0
        if mesh_cfg is None or mesh_cfg.num_devices == 1:
            self.mode = "plain"
            self.mesh_cfg = None
        elif mesh_cfg.tensor > 1:
            decode._validate_tp_mesh(cfg, mesh_cfg)
            self.mode = "tp"
            self._n_kv = cfg.kv_heads // mesh_cfg.tensor
        else:
            decode._validate_fsdp_mesh(mesh_cfg)
            self.mode = "zero3"
            self._prefetch_buffers = mesh_cfg.prefetch_buffers
        if self.mode != "plain":
            (
                self._mesh, self._p_specs, self._param_shardings
            ) = decode._mesh_param_shardings(cfg, self.mesh_cfg)
        # (kind, sampled) -> jitted program. Prefill additionally
        # specialises per bucket shape through jit's own shape cache, so
        # compile_count() reads len(buckets)-many entries off ONE program.
        self._programs: dict[tuple[str, bool], Any] = {}
        # batch -> dirty-but-reusable donated cache buffer. pool_caches
        # False (the compat shims) frees the cache after each request
        # instead — a shim engine exists per (cfg, max_len, mesh) and
        # lives forever in shim_engine's cache, so pooling there would
        # pin one full-size cache per distinct request shape; a real
        # serving deployment constructs ONE engine and wants the pool.
        self._pool_caches = pool_caches
        self._cache_pool: dict[int, decode.Cache] = {}

    # -- cache pool --------------------------------------------------------

    def new_cache(self, batch: int) -> decode.Cache:
        """Freshly-zeroed cache placed for this engine's mode (the pool
        bypasses this after the first request per batch size)."""
        if self.mode == "tp":
            # Global [L, B, S, Hkv, D] array sharded over the head dim:
            # each shard holds its LOCAL kv heads, matching the local
            # n_kv view forward sees inside shard_map.
            full = decode.init_cache(self.cfg, batch, self.max_len)
            return jax.device_put(full, self._cache_sharding())
        return decode.init_cache(
            self.cfg, batch, self.max_len, n_kv=self._n_kv
        )

    def _take_cache(self, batch: int) -> decode.Cache:
        return self._cache_pool.pop(batch, None) or self.new_cache(batch)

    def _return_cache(self, batch: int, cache: decode.Cache) -> None:
        if self._pool_caches:
            self._cache_pool[batch] = cache

    def _cache_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._cache_spec()
        return jax.tree.map(
            lambda s: NamedSharding(self._mesh, s), spec,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _cache_spec(self):
        from jax.sharding import PartitionSpec as P

        s = (
            P(None, None, None, "tensor", None)
            if self.mode == "tp"
            else P()
        )
        return {"k": s, "v": s}

    # -- program construction ---------------------------------------------

    def _forward(self, params, ids, cache, pos):
        kwargs = {}
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        elif self.mode == "zero3":
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            kwargs["block_transform"] = lambda bp: jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(a, replicated),
                bp,
            )
            kwargs["prefetch_buffers"] = self._prefetch_buffers
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self, sampled: bool):
        """The three raw program bodies for one greedy/sampled variant.
        Sampling scalars are always in the signature (greedy programs
        trace-and-drop them) so every program keys the same way."""

        def prefill(params, prompt, prompt_len, cache,
                    temperature, top_k, top_p, key):
            logits, cache = self._forward(params, prompt, cache, 0)
            last = jax.lax.dynamic_slice_in_dim(
                logits, prompt_len - 1, 1, axis=1
            )[:, 0]
            tok = decode.sample_token(
                last, sampled, temperature, key, top_k, top_p
            )
            return tok, cache

        def decode_run(params, tok, cache, pos, n_steps,
                       temperature, top_k, top_p, key):
            out = jnp.zeros((tok.shape[0], self.max_len), jnp.int32)

            def step(i, carry):
                out, cache, tok = carry
                logits, cache = self._forward(
                    params, tok[:, None], cache, pos + i
                )
                nxt = decode.sample_token(
                    logits[:, -1], sampled, temperature,
                    jax.random.fold_in(key, i), top_k, top_p,
                )
                return out.at[:, i].set(nxt), cache, nxt

            out, cache, _ = jax.lax.fori_loop(
                0, n_steps, step, (out, cache, tok)
            )
            return out, cache

        def decode_step(params, tok, cache, pos,
                        temperature, top_k, top_p, key):
            logits, cache = self._forward(params, tok[:, None], cache, pos)
            tok = decode.sample_token(
                logits[:, -1], sampled, temperature, key, top_k, top_p
            )
            return tok, cache

        return {
            "prefill": prefill,
            "decode_run": decode_run,
            "decode_step": decode_step,
        }

    # The cache's positional index in each program signature — the
    # donate_argnums every mode passes and the donation audit verifies.
    CACHE_ARGNUM = {"prefill": 3, "decode_run": 2, "decode_step": 2}

    def program(self, kind: str, sampled: bool):
        """The jitted program for (kind, greedy/sampled), built lazily.
        Public so the audit registry (analysis/registry.py) and tests can
        lower/compile the exact programs the engine dispatches."""
        if kind not in _PROGRAM_KINDS:
            raise KeyError(f"unknown program kind {kind!r}")
        prog = self._programs.get((kind, sampled))
        if prog is not None:
            return prog
        body = self._bodies(sampled)[kind]
        donate = (self.CACHE_ARGNUM[kind],)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        elif self.mode == "tp":
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = self._cache_spec()
            # Everything but the params and the head-sharded cache is
            # replicated; signatures per _bodies.
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), cache_spec, P(), P(), P(), P()
                ),
                "decode_run": (
                    self._p_specs, P(), cache_spec,
                    P(), P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(), P(), P(), P()
                ),
            }[kind]
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=(P(), cache_spec),
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        else:  # zero3
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            n_args = {"prefill": 8, "decode_run": 9, "decode_step": 8}[kind]
            in_sh = [replicated] * n_args
            in_sh[0] = self._param_shardings
            prog = jax.jit(
                body,
                in_shardings=tuple(in_sh),
                out_shardings=(replicated, replicated),
                donate_argnums=donate,
            )
        self._programs[(kind, sampled)] = prog
        return prog

    def _place_params(self, params):
        if self.mode == "plain":
            return params
        # No-op when already placed, so repeat calls pay nothing.
        return jax.device_put(params, self._param_shardings)

    # -- request API -------------------------------------------------------

    def _request_setup(self, prompt, max_new_tokens, temperature,
                       top_k, top_p):
        prompt = jnp.asarray(prompt)
        b, tp = prompt.shape
        if tp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine max_len {self.max_len}"
            )
        bucket = self.buckets.bucket_for(tp)
        padded = (
            prompt
            if bucket == tp
            else jnp.pad(prompt, ((0, 0), (0, bucket - tp)))
        )
        t, k, p = decode.sampling_scalars(
            temperature, top_k, top_p, self.cfg.vocab_size
        )
        return prompt, padded, b, tp, t, k, p

    def generate(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> jax.Array:
        """Serve one request: returns [B, Tp + max_new_tokens] — the same
        tokens the monolithic reference produces for this request."""
        early, key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key
        )
        if early is not None:
            return early
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        cache = self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)

        # A failed dispatch DROPS the buffer instead of pooling it: once
        # a program was dispatched its donated input is consumed whether
        # or not the call succeeded, so returning it would poison the
        # pool with a deleted array; the next request simply re-allocates
        # (the cost a healthy pool avoids, paid only after a failure).
        try:
            tok, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            pieces = [prompt.astype(jnp.int32), tok[:, None]]
            n = max_new_tokens - 1
            if n > 0:
                out, cache = self.program("decode_run", sampled)(
                    params, tok, cache, plen, jnp.asarray(n, jnp.int32),
                    t, k, p, key,
                )
                pieces.append(out[:, :n])
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)
        return jnp.concatenate(pieces, axis=1)

    def stream(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ):
        """Generator of [B] int32 token arrays, one per ``decode_step``
        dispatch — the streaming form of ``generate`` (identical tokens:
        same programs modulo the fused loop, same key folding). The cache
        buffer returns to the pool when the generator finishes or is
        closed."""
        early, key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key
        )
        if early is not None:
            return
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        cache = self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)
        # Same drop-on-dispatch-failure rule as generate(); an early
        # generator close (GeneratorExit at a yield) is NOT a failed
        # dispatch — `cache` is the last returned buffer and goes back
        # to the pool.
        try:
            tok, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            yield tok
            step = self.program("decode_step", sampled)
            for i in range(max_new_tokens - 1):
                tok, cache = step(
                    params, tok, cache, jnp.asarray(tp + i, jnp.int32),
                    t, k, p, jax.random.fold_in(key, i),
                )
                yield tok
        except GeneratorExit:
            raise
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)

    # -- introspection -----------------------------------------------------

    def compile_count(self) -> int:
        """Total compiled executables across the engine's programs (the
        number a mixed-length request stream is asserted against:
        n_buckets prefills + 1 decode program per greedy/sampled mode)."""
        return sum(p._cache_size() for p in self._programs.values())

    def example_args(self, kind: str, params, *, batch: int = 1,
                     prompt_len: int | None = None, sampled: bool = True):
        """Example argument tuple for (lowering/auditing) ``kind`` — the
        shapes ``generate`` dispatches with."""
        tp = prompt_len or min(
            self.buckets.buckets[0] if self.buckets.buckets else 4,
            self.max_len - 1,
        )
        bucket = self.buckets.bucket_for(tp)
        t, k, p = decode.sampling_scalars(
            0.8 if sampled else 0.0, None, None, self.cfg.vocab_size
        )
        cache = self.new_cache(batch)
        key = jax.random.key(0)
        plen = jnp.asarray(tp, jnp.int32)
        prompt = jnp.zeros((batch, bucket), jnp.int32)
        tok = jnp.zeros((batch,), jnp.int32)
        if kind == "prefill":
            return (params, prompt, plen, cache, t, k, p, key)
        if kind == "decode_run":
            return (
                params, tok, cache, plen, jnp.asarray(2, jnp.int32),
                t, k, p, key,
            )
        if kind == "decode_step":
            return (params, tok, cache, plen, t, k, p, key)
        raise KeyError(f"unknown program kind {kind!r}")

    def verify_donation(self, params, *, batch: int = 1,
                        sampled: bool = True) -> dict[str, dict]:
        """Prove the KV cache actually aliases in/out of every engine
        program: lower + compile each (without running) and check the
        compiled module's input_output_alias map covers every cache leaf.
        Raises RuntimeError naming the program otherwise — a silently
        rejected donation would double-buffer the cache on every step.
        Returns {kind: alias stats} for reporting."""
        from pytorch_distributed_tpu.analysis.audit import check_donation

        stats_all: dict[str, dict] = {}
        for kind in _PROGRAM_KINDS:
            args = self.example_args(
                kind, params, batch=batch, sampled=sampled
            )
            compiled = self.program(kind, sampled).lower(*args).compile()
            findings, stats = check_donation(
                compiled.as_text(), args, (self.CACHE_ARGNUM[kind],),
                strict=True,
            )
            stats_all[kind] = stats
            if findings:
                raise RuntimeError(
                    f"engine program {kind!r} ({self.mode}): donated KV "
                    "cache does not fully alias in the compiled "
                    f"executable — {findings[0].message}"
                )
        return stats_all


@functools.lru_cache(maxsize=None)
def shim_engine(
    cfg: ModelConfig, max_len: int, mesh_cfg: MeshConfig | None = None
) -> DecodeEngine:
    """Engine cache backing the models/decode.generate* compat shims:
    exact-length buckets (identical compile behaviour to the old
    monolithic entry — one prefill compile per distinct prompt length)
    and one engine per (cfg, max_len, mesh). Cache pooling is OFF so a
    shim call frees its cache like the old jit-internal path did — these
    engines live forever in this lru_cache, and a pooled cache per
    distinct (max_len, batch) would grow device memory with request
    diversity. Real serving loops should construct a DecodeEngine
    directly with a fixed max_len and power-of-two buckets (pooling on)."""
    return DecodeEngine(
        cfg, max_len=max_len, mesh_cfg=mesh_cfg, pool_caches=False
    )
