"""Persistent donated-KV decode engine: the serving fast path.

The monolithic ``generate`` programs (models/decode.py) are the wrong
shape for a serving loop: the KV cache is jit-internal (re-allocated and
re-zeroed every request), every distinct prompt length compiles a fresh
prefill+loop program, and — before this PR — every sampling config change
recompiled too. ``DecodeEngine`` restructures generation into two
long-lived compiled programs, the shape TPU serving practice settles on
(Fine-Tuning and Serving Gemma on Cloud TPU; the pjit-scaling playbook —
PAPERS.md):

- ``prefill(params, prompt, prompt_len, cache, t, k, p, key)``
  runs the whole (bucket-padded) prompt and samples the first token;
- ``decode_run(params, tok, cache, pos, n, t, k, p, key)``
  runs n single-token steps in one dispatch (a fori_loop with a TRACED
  trip count — one compile covers every generation length);
- ``decode_step(...)`` is the single-step form behind ``stream()``.

Three levers, all machine-checked:

1. **Buffer donation**: the cache is ``donate_argnums``-donated through
   every program, and the engine keeps the returned buffer in a pool —
   steady-state serving allocates and zero-fills NOTHING per request.
   Reusing a dirty buffer is sound because decode's cache discipline
   (models/decode.py) masks key positions > pos and overwrites each row
   before it becomes readable; tests/test_serving.py pins it, including
   the GQA edge. Donation is verified to actually alias in the compiled
   executable (``verify_donation`` + the strict mode of
   analysis/audit.check_donation) — a silently-rejected alias would
   double-buffer the largest tensor in the server.
2. **Bounded compilation**: prompts are padded to a small set of
   ``BucketSpec`` lengths (default powers of two), so steady-state
   serving compiles O(buckets) prefill programs + ONE decode program —
   not O(requests). Sampling params are traced scalars
   (decode.sampling_scalars); only greedy-vs-sampled is static.
3. **Comm/compute overlap (ZeRO-3 mode)**: decode from full-shard
   training layouts routes the layer scan through
   ops/layer_scan.scan_layers's windowed double-buffer schedule
   (``MeshConfig.prefetch_buffers``), so layer l+1's param all-gathers
   stream in under layer l's compute — the decode-side twin of the
   explicit training path's prefetch (closes ROADMAP PR-3 follow-up (c)).

Modes (one engine per mode x config):
- plain: single device, whole params.
- tp (``mesh_cfg.tensor`` > 1): shard_map over a "tensor" mesh, Megatron
  layouts, local-head cache shards (the cache pytree is a GLOBAL array
  sharded over the head dim — 1/tp of the cache HBM per chip).
- zero3 (``mesh_cfg.fsdp`` > 1, full_shard): auto-partitioned decode in
  the ZeRO-3 training layout with the windowed gather schedule above.
TP x ZeRO-3 mixed meshes are rejected up front with a diagnostic naming
these modes (``_reject_tp_zero3_mix``); native composition is future
surface.

Two engines share this machinery:
- ``DecodeEngine`` — serial: one request (of any batch) at a time, with
  an LRU-BOUNDED dirty-cache pool across requests.
- ``BatchedDecodeEngine`` — continuous batching: a fixed pool of slot
  ROWS inside one (slots, max_len) cache, a host-side scheduler that
  admits/retires requests per row, per-row traced positions and sampling
  state, and ONE compiled decode step advancing every row per dispatch.
  See its class docstring; this is the engine that fills the batch
  dimension under real multi-tenant traffic.

Outputs are bit-equal to the monolithic reference paths for identical
requests (greedy and fixed-key sampled) — same forward, same sampler,
same key-folding schedule; padded prompt rows and pooled-buffer garbage
are masked out of every reduction. Pinned by tests/test_serving.py.

Not thread-safe: the cache pool hands the SAME buffer to concurrent
requests of one batch size. Serialise requests per engine (or shard
engines per worker).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig
from pytorch_distributed_tpu.models import decode

_PROGRAM_KINDS = ("prefill", "decode_run", "decode_step")
_BATCHED_PROGRAM_KINDS = ("prefill", "decode_step")


def _reject_tp_zero3_mix(mesh_cfg: MeshConfig | None, entry: str) -> None:
    """Both serving entry points reject the TP x ZeRO-3 mixed mesh with
    one diagnostic naming the supported modes (ROADMAP serving follow-up
    (c)): decoding from a mixed layout needs each gathered layer window
    re-split over the tensor axis inside the token loop — a schedule
    neither the shard_map TP path nor the auto-partitioned ZeRO-3 path
    expresses today. Full composition is future surface."""
    if mesh_cfg is not None and mesh_cfg.tensor > 1 and mesh_cfg.fsdp > 1:
        raise NotImplementedError(
            f"{entry} does not support TP x ZeRO-3 mixed-mesh decode "
            f"(got tensor={mesh_cfg.tensor}, fsdp={mesh_cfg.fsdp}). "
            "Supported modes: plain (single device / no mesh), tp "
            "(tensor-only mesh, Megatron layouts with a head-sharded KV "
            "cache), and zero3 (fsdp-only full_shard mesh, DecodeEngine "
            "only). Serve a mixed-mesh checkpoint by resharding to one "
            "of those layouts; native composition is a future PR."
        )


def _select_mode(
    cfg: ModelConfig, mesh_cfg: MeshConfig | None, *,
    entry: str, allow_zero3: bool = True,
):
    """Shared engine mode selection: (mode, mesh_cfg, n_kv,
    prefetch_buffers), with the mixed-mesh rejection applied first so
    both engines emit the same diagnostic."""
    _reject_tp_zero3_mix(mesh_cfg, entry)
    if mesh_cfg is None or mesh_cfg.num_devices == 1:
        return "plain", None, None, 0
    if mesh_cfg.tensor > 1:
        decode._validate_tp_mesh(cfg, mesh_cfg)
        return "tp", mesh_cfg, cfg.kv_heads // mesh_cfg.tensor, 0
    if not allow_zero3:
        raise NotImplementedError(
            f"{entry} supports plain and tp modes; ZeRO-3 slot-batched "
            "decode is future surface — serve ZeRO-3 layouts through "
            "DecodeEngine, or decode from a tensor-only mesh"
        )
    decode._validate_fsdp_mesh(mesh_cfg)
    return "zero3", mesh_cfg, None, mesh_cfg.prefetch_buffers


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """Prompt-length buckets. A request of length T compiles (at most)
    the program of the smallest bucket >= T; ``()`` means exact-length
    (no padding — one compile per distinct length, the compat-shim
    behaviour)."""

    buckets: tuple[int, ...] = ()

    def __post_init__(self) -> None:
        b = tuple(self.buckets)
        if any(x <= 0 for x in b) or list(b) != sorted(set(b)):
            raise ValueError(
                f"buckets must be strictly increasing positives, got {b}"
            )
        object.__setattr__(self, "buckets", b)

    @classmethod
    def powers_of_two(
        cls, max_len: int, min_bucket: int = 128
    ) -> "BucketSpec":
        """128/256/.../max_len (first bucket = min_bucket clipped to
        max_len; max_len itself is always the last bucket so every
        admissible prompt has a home)."""
        if min_bucket <= 0 or max_len <= 0:
            raise ValueError("min_bucket and max_len must be positive")
        out = []
        b = min_bucket
        while b < max_len:
            out.append(b)
            b *= 2
        out.append(max_len)
        return cls(tuple(out))

    def bucket_for(self, length: int) -> int:
        if not self.buckets:
            return length
        for b in self.buckets:
            if b >= length:
                return b
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.buckets[-1]}"
        )


class DecodeEngine:
    """See module docstring. Construct once per (cfg, max_len, bucket
    spec, mesh); call ``generate`` / ``stream`` per request with any
    params matching ``cfg`` (params are call arguments, not engine state,
    so one engine serves many checkpoints of one architecture)."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_len: int,
        buckets: BucketSpec | None = None,
        mesh_cfg: MeshConfig | None = None,
        pool_caches: bool = True,
        pool_max_entries: int = 8,
    ) -> None:
        if max_len > cfg.n_ctx:
            raise ValueError(
                f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}"
            )
        self.cfg = cfg
        self.max_len = int(max_len)
        self.buckets = buckets or BucketSpec()
        if self.buckets.buckets and self.buckets.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket {self.buckets.buckets[-1]} exceeds "
                f"max_len {max_len}"
            )
        self.mode, self.mesh_cfg, self._n_kv, self._prefetch_buffers = (
            _select_mode(cfg, mesh_cfg, entry="DecodeEngine")
        )
        if self.mode != "plain":
            (
                self._mesh, self._p_specs, self._param_shardings
            ) = decode._mesh_param_shardings(cfg, self.mesh_cfg)
        # (kind, sampled) -> jitted program. Prefill additionally
        # specialises per bucket shape through jit's own shape cache, so
        # compile_count() reads len(buckets)-many entries off ONE program.
        self._programs: dict[tuple[str, bool], Any] = {}
        # batch -> dirty-but-reusable donated cache buffer. pool_caches
        # False (the compat shims) frees the cache after each request
        # instead — a shim engine exists per (cfg, max_len, mesh) and
        # lives forever in shim_engine's cache, so pooling there would
        # pin one full-size cache per distinct request shape; a real
        # serving deployment constructs ONE engine and wants the pool.
        # The pool is LRU-BOUNDED at pool_max_entries distinct batch
        # shapes (ROADMAP serving follow-up (d)): a traffic mix cycling
        # through many batch sizes caps pooled-cache HBM at
        # pool_max_entries x max_len-cache bytes instead of growing with
        # shape diversity; the least-recently-returned shape is dropped
        # (freed by the allocator once the array is unreferenced).
        self._pool_caches = pool_caches
        if pool_max_entries < 1:
            raise ValueError(
                f"pool_max_entries must be >= 1, got {pool_max_entries}"
            )
        self._pool_max = int(pool_max_entries)
        self._cache_pool: dict[int, decode.Cache] = {}

    # -- cache pool --------------------------------------------------------

    def new_cache(self, batch: int) -> decode.Cache:
        """Freshly-zeroed cache placed for this engine's mode (the pool
        bypasses this after the first request per batch size)."""
        if self.mode == "tp":
            # Global [L, B, S, Hkv, D] array sharded over the head dim:
            # each shard holds its LOCAL kv heads, matching the local
            # n_kv view forward sees inside shard_map.
            full = decode.init_cache(self.cfg, batch, self.max_len)
            return jax.device_put(full, self._cache_sharding())
        return decode.init_cache(
            self.cfg, batch, self.max_len, n_kv=self._n_kv
        )

    def _take_cache(self, batch: int) -> decode.Cache:
        return self._cache_pool.pop(batch, None) or self.new_cache(batch)

    def _return_cache(self, batch: int, cache: decode.Cache) -> None:
        if not self._pool_caches:
            return
        # Most-recently-used at the end (dict preserves insertion order);
        # evict from the front once the pool exceeds its LRU bound.
        self._cache_pool.pop(batch, None)
        self._cache_pool[batch] = cache
        while len(self._cache_pool) > self._pool_max:
            self._cache_pool.pop(next(iter(self._cache_pool)))

    def _cache_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._cache_spec()
        return jax.tree.map(
            lambda s: NamedSharding(self._mesh, s), spec,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _cache_spec(self):
        from jax.sharding import PartitionSpec as P

        s = (
            P(None, None, None, "tensor", None)
            if self.mode == "tp"
            else P()
        )
        return {"k": s, "v": s}

    # -- program construction ---------------------------------------------

    def _forward(self, params, ids, cache, pos):
        kwargs = {}
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        elif self.mode == "zero3":
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            kwargs["block_transform"] = lambda bp: jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(a, replicated),
                bp,
            )
            kwargs["prefetch_buffers"] = self._prefetch_buffers
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self, sampled: bool):
        """The three raw program bodies for one greedy/sampled variant.
        Sampling scalars are always in the signature (greedy programs
        trace-and-drop them) so every program keys the same way."""

        def prefill(params, prompt, prompt_len, cache,
                    temperature, top_k, top_p, key):
            logits, cache = self._forward(params, prompt, cache, 0)
            last = jax.lax.dynamic_slice_in_dim(
                logits, prompt_len - 1, 1, axis=1
            )[:, 0]
            tok = decode.sample_token(
                last, sampled, temperature, key, top_k, top_p
            )
            return tok, cache

        def decode_run(params, tok, cache, pos, n_steps,
                       temperature, top_k, top_p, key):
            out = jnp.zeros((tok.shape[0], self.max_len), jnp.int32)

            def step(i, carry):
                out, cache, tok = carry
                logits, cache = self._forward(
                    params, tok[:, None], cache, pos + i
                )
                nxt = decode.sample_token(
                    logits[:, -1], sampled, temperature,
                    jax.random.fold_in(key, i), top_k, top_p,
                )
                return out.at[:, i].set(nxt), cache, nxt

            out, cache, _ = jax.lax.fori_loop(
                0, n_steps, step, (out, cache, tok)
            )
            return out, cache

        def decode_step(params, tok, cache, pos,
                        temperature, top_k, top_p, key):
            logits, cache = self._forward(params, tok[:, None], cache, pos)
            tok = decode.sample_token(
                logits[:, -1], sampled, temperature, key, top_k, top_p
            )
            return tok, cache

        return {
            "prefill": prefill,
            "decode_run": decode_run,
            "decode_step": decode_step,
        }

    # The cache's positional index in each program signature — the
    # donate_argnums every mode passes and the donation audit verifies.
    CACHE_ARGNUM = {"prefill": 3, "decode_run": 2, "decode_step": 2}

    def program(self, kind: str, sampled: bool):
        """The jitted program for (kind, greedy/sampled), built lazily.
        Public so the audit registry (analysis/registry.py) and tests can
        lower/compile the exact programs the engine dispatches."""
        if kind not in _PROGRAM_KINDS:
            raise KeyError(f"unknown program kind {kind!r}")
        prog = self._programs.get((kind, sampled))
        if prog is not None:
            return prog
        body = self._bodies(sampled)[kind]
        donate = (self.CACHE_ARGNUM[kind],)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        elif self.mode == "tp":
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = self._cache_spec()
            # Everything but the params and the head-sharded cache is
            # replicated; signatures per _bodies.
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), cache_spec, P(), P(), P(), P()
                ),
                "decode_run": (
                    self._p_specs, P(), cache_spec,
                    P(), P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(), P(), P(), P()
                ),
            }[kind]
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=(P(), cache_spec),
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        else:  # zero3
            from jax.sharding import NamedSharding, PartitionSpec as P

            replicated = NamedSharding(self._mesh, P())
            n_args = {"prefill": 8, "decode_run": 9, "decode_step": 8}[kind]
            in_sh = [replicated] * n_args
            in_sh[0] = self._param_shardings
            prog = jax.jit(
                body,
                in_shardings=tuple(in_sh),
                out_shardings=(replicated, replicated),
                donate_argnums=donate,
            )
        self._programs[(kind, sampled)] = prog
        return prog

    def _place_params(self, params):
        if self.mode == "plain":
            return params
        # No-op when already placed, so repeat calls pay nothing.
        return jax.device_put(params, self._param_shardings)

    # -- request API -------------------------------------------------------

    def _request_setup(self, prompt, max_new_tokens, temperature,
                       top_k, top_p):
        prompt = jnp.asarray(prompt)
        b, tp = prompt.shape
        if tp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine max_len {self.max_len}"
            )
        bucket = self.buckets.bucket_for(tp)
        padded = (
            prompt
            if bucket == tp
            else jnp.pad(prompt, ((0, 0), (0, bucket - tp)))
        )
        t, k, p = decode.sampling_scalars(
            temperature, top_k, top_p, self.cfg.vocab_size
        )
        return prompt, padded, b, tp, t, k, p

    def generate(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ) -> jax.Array:
        """Serve one request: returns [B, Tp + max_new_tokens] — the same
        tokens the monolithic reference produces for this request."""
        early, key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key
        )
        if early is not None:
            return early
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        cache = self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)

        # A failed dispatch DROPS the buffer instead of pooling it: once
        # a program was dispatched its donated input is consumed whether
        # or not the call succeeded, so returning it would poison the
        # pool with a deleted array; the next request simply re-allocates
        # (the cost a healthy pool avoids, paid only after a failure).
        try:
            tok, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            pieces = [prompt.astype(jnp.int32), tok[:, None]]
            n = max_new_tokens - 1
            if n > 0:
                out, cache = self.program("decode_run", sampled)(
                    params, tok, cache, plen, jnp.asarray(n, jnp.int32),
                    t, k, p, key,
                )
                pieces.append(out[:, :n])
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)
        return jnp.concatenate(pieces, axis=1)

    def stream(
        self,
        params,
        prompt: jax.Array,  # [B, Tp] int
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
    ):
        """Generator of [B] int32 token arrays, one per ``decode_step``
        dispatch — the streaming form of ``generate`` (identical tokens:
        same programs modulo the fused loop, same key folding). The cache
        buffer returns to the pool when the generator finishes or is
        closed."""
        early, key = decode._check_sample_args(
            prompt, max_new_tokens, temperature, key
        )
        if early is not None:
            return
        prompt, padded, b, tp, t, k, p = self._request_setup(
            prompt, max_new_tokens, temperature, top_k, top_p
        )
        sampled = temperature > 0
        params = self._place_params(params)
        cache = self._take_cache(b)
        plen = jnp.asarray(tp, jnp.int32)
        # Same drop-on-dispatch-failure rule as generate(); an early
        # generator close (GeneratorExit at a yield) is NOT a failed
        # dispatch — `cache` is the last returned buffer and goes back
        # to the pool.
        try:
            tok, cache = self.program("prefill", sampled)(
                params, padded, plen, cache, t, k, p, key
            )
            yield tok
            step = self.program("decode_step", sampled)
            for i in range(max_new_tokens - 1):
                tok, cache = step(
                    params, tok, cache, jnp.asarray(tp + i, jnp.int32),
                    t, k, p, jax.random.fold_in(key, i),
                )
                yield tok
        except GeneratorExit:
            raise
        except BaseException:
            cache = None
            raise
        finally:
            if cache is not None:
                self._return_cache(b, cache)

    # -- introspection -----------------------------------------------------

    def compile_count(self) -> int:
        """Total compiled executables across the engine's programs (the
        number a mixed-length request stream is asserted against:
        n_buckets prefills + 1 decode program per greedy/sampled mode)."""
        return sum(p._cache_size() for p in self._programs.values())

    def example_args(self, kind: str, params, *, batch: int = 1,
                     prompt_len: int | None = None, sampled: bool = True):
        """Example argument tuple for (lowering/auditing) ``kind`` — the
        shapes ``generate`` dispatches with."""
        tp = prompt_len or min(
            self.buckets.buckets[0] if self.buckets.buckets else 4,
            self.max_len - 1,
        )
        bucket = self.buckets.bucket_for(tp)
        t, k, p = decode.sampling_scalars(
            0.8 if sampled else 0.0, None, None, self.cfg.vocab_size
        )
        cache = self.new_cache(batch)
        key = jax.random.key(0)
        plen = jnp.asarray(tp, jnp.int32)
        prompt = jnp.zeros((batch, bucket), jnp.int32)
        tok = jnp.zeros((batch,), jnp.int32)
        if kind == "prefill":
            return (params, prompt, plen, cache, t, k, p, key)
        if kind == "decode_run":
            return (
                params, tok, cache, plen, jnp.asarray(2, jnp.int32),
                t, k, p, key,
            )
        if kind == "decode_step":
            return (params, tok, cache, plen, t, k, p, key)
        raise KeyError(f"unknown program kind {kind!r}")

    def verify_donation(self, params, *, batch: int = 1,
                        sampled: bool = True) -> dict[str, dict]:
        """Prove the KV cache actually aliases in/out of every engine
        program: lower + compile each (without running) and check the
        compiled module's input_output_alias map covers every cache leaf.
        Raises RuntimeError naming the program otherwise — a silently
        rejected donation would double-buffer the cache on every step.
        Returns {kind: alias stats} for reporting."""
        from pytorch_distributed_tpu.analysis.audit import check_donation

        stats_all: dict[str, dict] = {}
        for kind in _PROGRAM_KINDS:
            args = self.example_args(
                kind, params, batch=batch, sampled=sampled
            )
            compiled = self.program(kind, sampled).lower(*args).compile()
            findings, stats = check_donation(
                compiled.as_text(), args, (self.CACHE_ARGNUM[kind],),
                strict=True,
            )
            stats_all[kind] = stats
            if findings:
                raise RuntimeError(
                    f"engine program {kind!r} ({self.mode}): donated KV "
                    "cache does not fully alias in the compiled "
                    f"executable — {findings[0].message}"
                )
        return stats_all


@dataclasses.dataclass
class _Pending:
    """A queued request (host-side): everything the prefill dispatch
    needs, encoded once at submit time."""

    rid: int
    prompt: np.ndarray  # [Tp] int32
    bucket: int
    max_new: int
    eos_id: int | None
    greedy: bool
    t: float
    k: int
    p: float
    keydata: np.ndarray  # key-impl uint32 words


@dataclasses.dataclass
class _Slot:
    """One occupied row of the slot batch (host-side scheduler state)."""

    rid: int
    prompt: np.ndarray
    max_new: int
    eos_id: int | None
    pos: int  # tokens in the row's cache = next KV write offset
    fold: int  # fold_in counter for the row's NEXT sampled draw
    generated: list
    greedy: bool
    t: float
    k: int
    p: float
    keydata: np.ndarray


class BatchedDecodeEngine:
    """Continuous batching: slot-scheduled multi-request decode.

    ``DecodeEngine`` serves one request shape at a time — under real
    traffic the batch dimension idles while requests queue. This engine
    keeps ONE long-lived ``(slots, max_len)`` KV cache whose rows are
    independent requests at unrelated depths: a host-side scheduler
    admits queued prompts into free rows (bucketed per-row prefill, or
    one batched prefill when several arrivals share a bucket), a single
    compiled ``decode_step`` advances ALL rows one token per dispatch,
    and finished rows retire without touching their neighbours. Every
    per-row quantity — position, fold counter, greedy flag,
    temperature/top_k/top_p, PRNG key — is a TRACED [slots] operand, so
    admissions, retirements, sampling-config changes, and any
    active-row pattern reuse the same executables: steady-state serving
    is zero-recompile BY CONSTRUCTION (shapes never change — the pjit
    fixed-shape compilation discipline), and the collective count of the
    TP program is invariant to how many rows are active (pinned in the
    audit registry).

    Soundness of row reuse is the PR-4 dirty-cache discipline at ROW
    granularity: a retired row's K/V stays in place; the next admission
    prefills over it, and per-row masking (``decode._cached_attention``
    with a [B] pos vector) guarantees no row ever reads cache positions
    past its own write point — including the GQA head-repeat edge
    (tests/test_serving_batched.py).

    The decode program is deliberately OBLIVIOUS to which rows are
    active: free rows compute garbage that the host discards. Gating
    them with a mask would save nothing (the shapes are fixed) and would
    make program behaviour depend on activity — exactly what the
    zero-recompile and collective-count contracts forbid. ``active`` is
    therefore host-side scheduler state, not a program operand.

    Modes: plain and tp (head-sharded global cache — 1/tp of the cache
    HBM per chip). ZeRO-3 slot batching and TP x ZeRO-3 stay rejected
    with explicit diagnostics (``_select_mode``). MoE configs are
    rejected: expert capacity couples rows through the dispatch (a busy
    neighbour could evict a row's tokens), breaking the per-row
    independence this engine is built on.

    Unlike the serial engine there is no greedy/sampled program split:
    one batch serves both kinds of row, so greedy is a traced per-row
    flag and the full-vocab sort always runs (see
    ``decode.sample_token_rows``). Program count: ONE decode_step shape
    + (buckets x prefill group sizes) prefill shapes — compile_count()
    is asserted flat across admit/retire churn in tests.

    Not thread-safe (single dispatcher per engine); requests are
    single-sequence (one row each — batch your own beams as separate
    requests).
    """

    # The donated cache's positional index in each program signature.
    CACHE_ARGNUM = {"prefill": 4, "decode_step": 2}

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        slots: int,
        max_len: int,
        buckets: BucketSpec | None = None,
        mesh_cfg: MeshConfig | None = None,
        prefill_groups: tuple[int, ...] | None = None,
    ) -> None:
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_len > cfg.n_ctx:
            raise ValueError(f"max_len {max_len} exceeds n_ctx {cfg.n_ctx}")
        if cfg.n_experts:
            raise NotImplementedError(
                "BatchedDecodeEngine does not serve MoE configs: expert "
                "capacity couples batch rows through the dispatch, so a "
                "row's output would depend on its neighbours — use the "
                "serial DecodeEngine for MoE decode"
            )
        self.cfg = cfg
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.buckets = buckets or BucketSpec()
        if self.buckets.buckets and self.buckets.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket {self.buckets.buckets[-1]} exceeds "
                f"max_len {max_len}"
            )
        if prefill_groups is None:
            # Powers of two up to the slot count: a burst of n same-bucket
            # arrivals pads to the next group size, so prefill compiles
            # O(buckets x log slots) shapes, not O(buckets x slots).
            groups = []
            g = 1
            while g < self.slots:
                groups.append(g)
                g *= 2
            groups.append(self.slots)
            prefill_groups = tuple(groups)
        pg = tuple(sorted(set(int(g) for g in prefill_groups)))
        if not pg or pg[0] < 1 or pg[-1] < self.slots:
            raise ValueError(
                f"prefill_groups must be positive and cover the slot "
                f"count {self.slots}, got {prefill_groups}"
            )
        self._groups = pg
        self.mode, self.mesh_cfg, self._n_kv, _ = _select_mode(
            cfg, mesh_cfg, entry="BatchedDecodeEngine", allow_zero3=False
        )
        if self.mode == "tp":
            (
                self._mesh, self._p_specs, self._param_shardings
            ) = decode._mesh_param_shardings(cfg, self.mesh_cfg)
        self._programs: dict[str, Any] = {}
        # ONE cache for the engine's whole life, donated through every
        # dispatch — HBM is bounded at exactly one (slots, max_len) cache
        # by construction (no pool to bound). None = not yet allocated,
        # or dropped after a failed dispatch (the donated input is
        # consumed either way; the next dispatch re-allocates zeros and
        # per-row masking makes the lost garbage irrelevant — but the
        # in-flight rows lost their K/V, so a failure aborts them).
        self._cache: decode.Cache | None = None
        self._key_words = np.asarray(
            jax.random.key_data(jax.random.key(0))
        ).shape[-1]
        self._queue: collections.deque[_Pending] = collections.deque()
        self._slots: list[_Slot | None] = [None] * self.slots
        self._next_rid = 0
        # (source tree, placed tree): _place_params runs once per
        # scheduler tick — one jax.device_put tree traversal per TOKEN
        # without this identity memo (the serial engine pays it once per
        # request; holding the source keeps its id from being recycled).
        self._placed: tuple[Any, Any] | None = None
        self.results: dict[int, np.ndarray] = {}
        self.aborted: set[int] = set()

    # -- cache -------------------------------------------------------------

    def _new_cache(self) -> decode.Cache:
        if self.mode == "tp":
            full = decode.init_cache(self.cfg, self.slots, self.max_len)
            from jax.sharding import NamedSharding, PartitionSpec as P

            spec = P(None, None, None, "tensor", None)
            sharding = jax.tree.map(
                lambda s: NamedSharding(self._mesh, s),
                {"k": spec, "v": spec},
                is_leaf=lambda x: isinstance(x, P),
            )
            return jax.device_put(full, sharding)
        return decode.init_cache(
            self.cfg, self.slots, self.max_len, n_kv=self._n_kv
        )

    def _take_cache(self) -> decode.Cache:
        cache, self._cache = self._cache, None
        return cache if cache is not None else self._new_cache()

    # -- programs ----------------------------------------------------------

    def _forward(self, params, ids, cache, pos):
        kwargs = {}
        if self.mode == "tp":
            kwargs["tensor_axis"] = "tensor"
        return decode.forward(params, ids, self.cfg, cache, pos, **kwargs)

    def _bodies(self):
        """The two raw program bodies. All sampling state is per-row and
        traced; ``rows``/``pos``/``folds`` are traced index vectors, so
        one compiled shape covers every admission/retirement pattern."""

        def prefill(params, prompts, plens, rows, cache,
                    greedy, t, k, p, keydata):
            # Gather the target rows' (dirty) segments, run the normal
            # prefill forward over them at pos 0, scatter back. Padded
            # group entries duplicate row index AND data, so the
            # overlapping scatter writes are identical (deterministic).
            seg = {kk: vv[:, rows] for kk, vv in cache.items()}
            logits, seg = self._forward(params, prompts, seg, 0)
            last = jnp.take_along_axis(
                logits, (plens - 1)[:, None, None], axis=1
            )[:, 0]
            keys = jax.random.wrap_key_data(keydata)
            tok = decode.sample_token_rows(last, greedy, t, keys, k, p)
            cache = {
                kk: cache[kk].at[:, rows].set(seg[kk]) for kk in cache
            }
            return tok, cache

        def decode_step(params, toks, cache, pos, folds,
                        greedy, t, k, p, keydata):
            logits, cache = self._forward(params, toks[:, None], cache, pos)
            keys = jax.vmap(jax.random.fold_in)(
                jax.random.wrap_key_data(keydata), folds
            )
            tok = decode.sample_token_rows(
                logits[:, -1], greedy, t, keys, k, p
            )
            return tok, cache

        return {"prefill": prefill, "decode_step": decode_step}

    def program(self, kind: str):
        """The jitted program for ``kind`` — public for the audit
        registry (analysis/registry.py) and tests, like
        ``DecodeEngine.program``."""
        if kind not in _BATCHED_PROGRAM_KINDS:
            raise KeyError(f"unknown batched program kind {kind!r}")
        prog = self._programs.get(kind)
        if prog is not None:
            return prog
        body = self._bodies()[kind]
        donate = (self.CACHE_ARGNUM[kind],)
        if self.mode == "plain":
            prog = jax.jit(body, donate_argnums=donate)
        else:  # tp
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.utils.compat import shard_map

            cache_spec = {
                "k": P(None, None, None, "tensor", None),
                "v": P(None, None, None, "tensor", None),
            }
            specs = {
                "prefill": (
                    self._p_specs, P(), P(), P(), cache_spec,
                    P(), P(), P(), P(), P(),
                ),
                "decode_step": (
                    self._p_specs, P(), cache_spec, P(), P(),
                    P(), P(), P(), P(), P(),
                ),
            }[kind]
            smapped = shard_map(
                body,
                mesh=self._mesh,
                in_specs=specs,
                out_specs=(P(), cache_spec),
                check_vma=True,
            )
            prog = jax.jit(smapped, donate_argnums=donate)
        self._programs[kind] = prog
        return prog

    def _place_params(self, params):
        if self.mode == "plain":
            return params
        if self._placed is None or self._placed[0] is not params:
            self._placed = (
                params, jax.device_put(params, self._param_shardings)
            )
        return self._placed[1]

    # -- request API -------------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        temperature: float = 0.0,
        key: jax.Array | None = None,
        top_k: int | None = None,
        top_p: float | None = None,
        eos_id: int | None = None,
    ) -> int:
        """Queue one single-sequence request ([Tp] or [1, Tp] int ids);
        returns its request id. The request is admitted into a free slot
        by a later ``step``; its output (prompt + generated ids, cut at
        ``eos_id`` if hit) lands in ``self.results[rid]`` — collect it
        with ``pop_result(rid)`` (long-lived engines leak host memory
        otherwise). Backpressure is the queue itself: submissions beyond
        the slot count simply wait their FIFO turn."""
        prompt = np.asarray(prompt)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1:
            raise ValueError(
                f"BatchedDecodeEngine serves one sequence per request "
                f"(one slot row); got prompt shape {prompt.shape}"
            )
        tp = prompt.shape[0]
        if tp == 0:
            raise ValueError(
                "empty prompt: need at least one token to prefill (an "
                "empty prompt would sample the first token from a pad "
                "position's logits)"
            )
        if max_new_tokens < 0:
            raise ValueError(
                f"max_new_tokens must be >= 0, got {max_new_tokens}"
            )
        if tp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({tp}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine max_len {self.max_len}"
            )
        if temperature > 0.0 and key is None:
            raise ValueError("temperature sampling requires a PRNG key")
        rid = self._next_rid
        self._next_rid += 1
        if max_new_tokens == 0:
            self.results[rid] = prompt.astype(np.int32)
            return rid
        bucket = self.buckets.bucket_for(tp)
        t, k, p = decode.sampling_scalars(
            temperature, top_k, top_p, self.cfg.vocab_size
        )
        keydata = (
            np.asarray(jax.random.key_data(key))
            if key is not None
            else np.zeros((self._key_words,), np.uint32)
        )
        self._queue.append(_Pending(
            rid=rid, prompt=prompt.astype(np.int32), bucket=bucket,
            max_new=int(max_new_tokens), eos_id=eos_id,
            greedy=not temperature > 0.0,
            t=float(t), k=int(k), p=float(p), keydata=keydata,
        ))
        return rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(
            s is not None for s in self._slots
        )

    def queued_rids(self) -> list[int]:
        return [q.rid for q in self._queue]

    def active_rids(self) -> list[int]:
        return [s.rid for s in self._slots if s is not None]

    def step(self, params) -> list[int]:
        """One scheduler tick: admit queued requests into free slots
        (prefill), then advance every active row one token (one batched
        decode dispatch). Returns the rids that finished this tick."""
        params = self._place_params(params)
        finished: list[int] = []
        self._admit(params, finished)
        if any(s is not None for s in self._slots):
            self._decode_tick(params, finished)
        return finished

    def run(self, params, requests=None) -> dict[int, np.ndarray]:
        """Submit ``requests`` (iterable of ``submit`` kwarg dicts), then
        drive ``step`` until idle. Returns {rid: tokens} for everything
        completed during the drive (including previously queued work)."""
        before = set(self.results)
        for req in requests or ():
            self.submit(**req)
        while self.has_work():
            self.step(params)
        return {
            rid: out for rid, out in self.results.items()
            if rid not in before
        }

    def pop_result(self, rid: int) -> np.ndarray | None:
        """Deliver and RELEASE one request's output: returns the tokens
        (``None`` if the request was aborted by a failed dispatch) and
        drops the engine's reference. A long-lived engine retains every
        retired request's output in ``results`` (and aborted rids in
        ``aborted``) until delivered — serving loops must pop (or ``del``)
        what they consume, or host memory grows per request forever."""
        if rid in self.aborted:
            self.aborted.discard(rid)
            return None
        return self.results.pop(rid)

    def warmup(self, params) -> int:
        """Compile every (bucket x prefill-group) shape plus the decode
        program with dummy dispatches (idle engines only — warmup writes
        garbage rows), so a serving loop's steady state starts
        compile-free. Returns compile_count()."""
        if self.has_work():
            raise RuntimeError("warmup requires an idle engine")
        if not self.buckets.buckets:
            raise ValueError(
                "warmup needs a finite BucketSpec (exact-length mode "
                "compiles per observed prompt length)"
            )
        params = self._place_params(params)
        for bucket in self.buckets.buckets:
            for g in self._groups:
                args = self.example_args(
                    "prefill", params, bucket=bucket, group=g,
                    cache=self._take_cache(),
                )
                _, cache = self.program("prefill")(*args)
                self._cache = cache
        args = self.example_args(
            "decode_step", params, cache=self._take_cache()
        )
        _, cache = self.program("decode_step")(*args)
        self._cache = cache
        return self.compile_count()

    # -- scheduler internals -----------------------------------------------

    def _admit(self, params, finished: list[int]) -> None:
        free = [i for i, s in enumerate(self._slots) if s is None]
        n = min(len(free), len(self._queue))
        if not n:
            return
        admitted = [self._queue.popleft() for _ in range(n)]
        # FIFO admission; arrivals sharing a bucket prefill as one
        # batched dispatch (group padded to the next allowed size).
        by_bucket: dict[int, list[tuple[_Pending, int]]] = {}
        for req in admitted:
            by_bucket.setdefault(req.bucket, []).append(
                (req, free.pop(0))
            )
        for bucket, group in by_bucket.items():
            self._prefill_group(params, bucket, group, finished)

    def _prefill_group(self, params, bucket, group, finished) -> None:
        n = len(group)
        npad = next(g for g in self._groups if g >= n)
        # Pad the group by DUPLICATING entry 0 (same row index, same
        # data): the overlapping scatter writes are bit-identical, and
        # the duplicate's sampled token is discarded.
        idx = list(range(n)) + [0] * (npad - n)
        prompts = np.zeros((npad, bucket), np.int32)
        plens = np.zeros((npad,), np.int32)
        rows = np.zeros((npad,), np.int32)
        greedy = np.zeros((npad,), np.bool_)
        t = np.ones((npad,), np.float32)
        k = np.full((npad,), self.cfg.vocab_size, np.int32)
        p = np.full((npad,), 2.0, np.float32)
        keydata = np.zeros((npad, self._key_words), np.uint32)
        for j, i in enumerate(idx):
            req, row = group[i]
            prompts[j, : req.prompt.shape[0]] = req.prompt
            plens[j] = req.prompt.shape[0]
            rows[j] = row
            greedy[j] = req.greedy
            t[j], k[j], p[j] = req.t, req.k, req.p
            keydata[j] = req.keydata
        toks = self._dispatch(
            "prefill", params, jnp.asarray(prompts), jnp.asarray(plens),
            jnp.asarray(rows), None, jnp.asarray(greedy), jnp.asarray(t),
            jnp.asarray(k), jnp.asarray(p), jnp.asarray(keydata),
        )
        toks = np.asarray(toks)
        for i, (req, row) in enumerate(group):
            self._slots[row] = _Slot(
                rid=req.rid, prompt=req.prompt, max_new=req.max_new,
                eos_id=req.eos_id, pos=int(plens[i]), fold=0,
                generated=[int(toks[i])], greedy=req.greedy,
                t=req.t, k=req.k, p=req.p, keydata=req.keydata,
            )
            self._maybe_retire(row, finished)

    def _decode_tick(self, params, finished: list[int]) -> None:
        b = self.slots
        toks = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        folds = np.zeros((b,), np.int32)
        greedy = np.ones((b,), np.bool_)
        t = np.ones((b,), np.float32)
        k = np.full((b,), self.cfg.vocab_size, np.int32)
        p = np.full((b,), 2.0, np.float32)
        keydata = np.zeros((b, self._key_words), np.uint32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue  # free rows decode garbage the host discards
            toks[i] = s.generated[-1]
            pos[i] = s.pos
            folds[i] = s.fold
            greedy[i] = s.greedy
            t[i], k[i], p[i] = s.t, s.k, s.p
            keydata[i] = s.keydata
        out = self._dispatch(
            "decode_step", params, jnp.asarray(toks), None,
            jnp.asarray(pos), jnp.asarray(folds), jnp.asarray(greedy),
            jnp.asarray(t), jnp.asarray(k), jnp.asarray(p),
            jnp.asarray(keydata),
        )
        out = np.asarray(out)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            s.generated.append(int(out[i]))
            s.pos += 1
            s.fold += 1
            self._maybe_retire(i, finished)

    def _dispatch(self, kind, params, *args):
        """Run ``kind`` with the engine cache spliced in at its donated
        argnum. A failed dispatch consumed the donated buffer, so the
        cache is dropped AND every in-flight row is aborted (its K/V is
        gone) — queued requests survive and admit into the fresh cache."""
        cache_at = self.CACHE_ARGNUM[kind] - 1  # args exclude params here
        args = list(args)
        args[cache_at] = self._take_cache()
        try:
            out, cache = self.program(kind)(params, *args)
        except BaseException:
            for i, s in enumerate(self._slots):
                if s is not None:
                    self.aborted.add(s.rid)
                    self._slots[i] = None
            raise
        self._cache = cache
        return out

    def _maybe_retire(self, row: int, finished: list[int]) -> None:
        s = self._slots[row]
        hit_eos = s.eos_id is not None and s.generated[-1] == s.eos_id
        if len(s.generated) < s.max_new and not hit_eos:
            return
        # Retirement is pure host bookkeeping: the row's K/V stays in
        # place (dirty) and the next admission masks it out.
        self.results[s.rid] = np.concatenate(
            [s.prompt, np.asarray(s.generated, np.int32)]
        )
        self._slots[row] = None
        finished.append(s.rid)

    # -- introspection -----------------------------------------------------

    def compile_count(self) -> int:
        """Total compiled executables across both programs: ONE
        decode_step + one prefill per (bucket, group) shape served. The
        churn tests assert this stays flat across admissions and
        retirements at a fixed slot count."""
        return sum(p._cache_size() for p in self._programs.values())

    def example_args(self, kind: str, params, *, bucket: int | None = None,
                     group: int = 1, cache: decode.Cache | None = None):
        """Example argument tuple for lowering/auditing ``kind`` — the
        shapes ``step`` dispatches with. ``cache=None`` allocates a
        fresh one (callers doing real dispatches should pass
        ``self._take_cache()`` and pocket the returned buffer)."""
        if cache is None:
            cache = self._new_cache()
        if kind == "prefill":
            b = bucket or (
                self.buckets.buckets[0] if self.buckets.buckets else 4
            )
            npad = next(g for g in self._groups if g >= group)
            return (
                params,
                jnp.zeros((npad, b), jnp.int32),
                jnp.ones((npad,), jnp.int32),
                jnp.zeros((npad,), jnp.int32),
                cache,
                jnp.ones((npad,), jnp.bool_),
                jnp.ones((npad,), jnp.float32),
                jnp.full((npad,), self.cfg.vocab_size, jnp.int32),
                jnp.full((npad,), 2.0, jnp.float32),
                jnp.zeros((npad, self._key_words), jnp.uint32),
            )
        if kind == "decode_step":
            b = self.slots
            return (
                params,
                jnp.zeros((b,), jnp.int32),
                cache,
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.bool_),
                jnp.ones((b,), jnp.float32),
                jnp.full((b,), self.cfg.vocab_size, jnp.int32),
                jnp.full((b,), 2.0, jnp.float32),
                jnp.zeros((b, self._key_words), jnp.uint32),
            )
        raise KeyError(f"unknown batched program kind {kind!r}")

    def verify_donation(self, params) -> dict[str, dict]:
        """Prove the slot cache actually aliases in/out of both batched
        programs (strict mode of the donation audit) — the engine-side
        twin of ``DecodeEngine.verify_donation``. A rejected alias would
        double-buffer the whole (slots, max_len) cache EVERY TOKEN."""
        from pytorch_distributed_tpu.analysis.audit import check_donation

        params = self._place_params(params)
        stats_all: dict[str, dict] = {}
        for kind in _BATCHED_PROGRAM_KINDS:
            args = self.example_args(kind, params)
            compiled = self.program(kind).lower(*args).compile()
            findings, stats = check_donation(
                compiled.as_text(), args, (self.CACHE_ARGNUM[kind],),
                strict=True,
            )
            stats_all[kind] = stats
            if findings:
                raise RuntimeError(
                    f"batched engine program {kind!r} ({self.mode}): "
                    "donated slot KV cache does not fully alias in the "
                    f"compiled executable — {findings[0].message}"
                )
        return stats_all


@functools.lru_cache(maxsize=None)
def shim_engine(
    cfg: ModelConfig, max_len: int, mesh_cfg: MeshConfig | None = None
) -> DecodeEngine:
    """Engine cache backing the models/decode.generate* compat shims:
    exact-length buckets (identical compile behaviour to the old
    monolithic entry — one prefill compile per distinct prompt length)
    and one engine per (cfg, max_len, mesh). Cache pooling is OFF so a
    shim call frees its cache like the old jit-internal path did — these
    engines live forever in this lru_cache, and a pooled cache per
    distinct (max_len, batch) would grow device memory with request
    diversity. Real serving loops should construct a DecodeEngine
    directly with a fixed max_len and power-of-two buckets (pooling on)."""
    return DecodeEngine(
        cfg, max_len=max_len, mesh_cfg=mesh_cfg, pool_caches=False
    )
