"""Asyncio HTTP/SSE front door over the replica router.

The engines and the router are library objects; a service needs a wire
protocol. This is a deliberately minimal HTTP/1.1 server on raw asyncio
streams — stdlib only (the rig bakes in no web framework, and a serving
tier whose failure modes we pin in tests should not hide behind one).
One background task drives ``router.step`` in a worker thread (the
dispatch blocks on device compute; the event loop must not); every
router mutation — submit, abort, admin actions, the step itself —
serialises through one lock, so the router keeps its single-dispatcher
contract under concurrent clients.

Endpoints:

- ``POST /v1/generate`` — body ``{"prompt": [ids...],
  "max_new_tokens": n, "temperature"?, "top_k"?, "top_p"?, "seed"?,
  "eos_id"?, "timeout_s"?, "stream"?, "priority"?, "tenant"?,
  "session"?}``. ``priority`` is the SLO tier
  (interactive/standard/batch — serving/scheduler.py), ``tenant`` a
  registered LoRA adapter id (serving/adapters.py), ``session`` a sid
  from ``/v1/session/open``; unknown priority classes, unregistered
  tenants, and diverged session resubmissions all reject 400 with the
  engine's diagnostic. The client deadline
  ``timeout_s`` maps straight onto ``submit(timeout_s=)`` — the engine
  clock enforces it queued AND mid-decode. Plain requests block until
  terminal and return ``{"rid", "state", "tokens", "reason"}``; with
  ``"stream": true`` the response is Server-Sent Events: one
  ``data: {"token": t}`` per generated token as the scheduler produces
  it, then ``event: done`` carrying the terminal result. A client that
  disconnects mid-stream ABORTS its request (the router frees the row;
  neighbours never notice).
- ``POST /v1/abort`` — ``{"rid": n}`` -> ``{"aborted": bool}``.
- ``POST /v1/session/open`` -> ``{"session": sid}`` /
  ``POST /v1/session/close`` ``{"session": sid}`` — the multi-turn
  chat surface: the router pins the session to one replica (its pages
  are the locality) and re-homes it on failover.
- ``GET /healthz`` — the router's ``stats()`` snapshot (replica states,
  queue/page pressure, counters): the probe a load balancer or an
  operator polls.
- ``POST /admin/kill|drain|restart`` — ``{"replica": i}``: the
  operator's chaos/maintenance handles (the README quickstart kills a
  replica mid-stream and watches the SSE stream keep going).

Overload: ``RouterOverloaded`` maps to ``429`` with a ``Retry-After``
header (integer seconds, ceiling) and the machine-readable
``retry_after_s`` in the JSON body — reject-loudly at the wire, exactly
like the router underneath.
"""

from __future__ import annotations

import asyncio
import json
import math
import threading
from typing import Any

import numpy as np

from pytorch_distributed_tpu.serving.lifecycle import RouterOverloaded
from pytorch_distributed_tpu.utils.logging import get_logger

_MAX_BODY = 1 << 22  # 4 MiB of JSON prompt is already absurd


class _HTTPError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


_STATUS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error",
}


class ServingServer:
    """See module docstring. ``router`` is a ``ReplicaRouter`` sharing
    ``params``; ``port=0`` binds an ephemeral port (read it off
    ``server.port`` after ``start`` — the tests do). ``idle_poll_s``
    bounds how long the drive loop sleeps when no work is queued, i.e.
    the worst-case latency from an empty router to the first prefill of
    a fresh request."""

    def __init__(
        self,
        router,
        params,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        default_max_new: int = 32,
        idle_poll_s: float = 0.02,
    ) -> None:
        self.router = router
        self.params = params
        self.host = host
        self.port = port
        self.default_max_new = int(default_max_new)
        self.idle_poll_s = float(idle_poll_s)
        self._lock = threading.Lock()  # serialises ALL router access
        self._server: asyncio.AbstractServer | None = None
        self._drive_task: asyncio.Task | None = None
        self._running = False
        # Terminal-result wakeups (one event per in-flight rid) + one
        # broadcast event per tick for SSE progress pollers.
        self._done_events: dict[int, asyncio.Event] = {}
        self._tick_event = asyncio.Event()
        self._work_event = asyncio.Event()
        self._log = get_logger("pdtpu.serving")

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._running = True
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._drive_task = asyncio.create_task(self._drive_loop())
        self._log.info(
            f"serving on http://{self.host}:{self.port} "
            f"({len(self.router.replica_states())} replicas)"
        )
        return self.host, self.port

    async def stop(self) -> None:
        self._running = False
        self._work_event.set()
        if self._drive_task is not None:
            await self._drive_task
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        await self.start()
        try:
            await self._server.serve_forever()
        finally:
            await self.stop()

    # -- scheduler drive ----------------------------------------------------

    def _locked(self, fn, *args, **kw):
        with self._lock:
            return fn(*args, **kw)

    async def _router_call(self, fn, *args, **kw):
        """Run one router operation in a worker thread under the lock —
        never block the event loop on the lock (a step mid-dispatch
        holds it for a whole engine tick)."""
        return await asyncio.to_thread(self._locked, fn, *args, **kw)

    async def _drive_loop(self) -> None:
        while self._running:
            has_work = await self._router_call(self.router.has_work)
            if not has_work:
                self._work_event.clear()
                try:
                    await asyncio.wait_for(
                        self._work_event.wait(), self.idle_poll_s
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    pass
                continue
            try:
                finished = await self._router_call(
                    self.router.step, self.params
                )
            except Exception:  # a dead fleet must not kill the server
                self._log.exception("router step failed")
                await asyncio.sleep(self.idle_poll_s)
                continue
            for rid in finished:
                ev = self._done_events.pop(rid, None)
                if ev is not None:
                    ev.set()
            tick_ev, self._tick_event = self._tick_event, asyncio.Event()
            tick_ev.set()

    # -- HTTP plumbing ------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            method, path, body = await self._read_request(reader)
            await self._route(method, path, body, writer)
        except _HTTPError as err:
            await self._send_json(
                writer, err.status, {"error": str(err)}
            )
        except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError):
            pass
        except Exception as err:  # noqa: BLE001 — wire boundary
            self._log.exception("request handler failed")
            try:
                await self._send_json(
                    writer, 500, {"error": f"{type(err).__name__}: {err}"}
                )
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            raise _HTTPError(400, "empty request")
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3:
            raise _HTTPError(400, f"malformed request line {line!r}")
        method, path, _version = parts
        headers = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name, _, value = hline.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", 0))
        if length > _MAX_BODY:
            raise _HTTPError(413, f"body {length} bytes > {_MAX_BODY}")
        raw = await reader.readexactly(length) if length else b""
        body: Any = None
        if raw:
            try:
                body = json.loads(raw)
            except json.JSONDecodeError as err:
                raise _HTTPError(400, f"invalid JSON body: {err}") from None
        return method, path, body

    async def _send_json(self, writer, status: int, obj,
                         extra_headers: tuple = ()) -> None:
        payload = json.dumps(obj).encode()
        head = [
            f"HTTP/1.1 {status} {_STATUS.get(status, 'Unknown')}",
            "Content-Type: application/json",
            f"Content-Length: {len(payload)}",
            "Connection: close",
            *extra_headers,
        ]
        writer.write(
            ("\r\n".join(head) + "\r\n\r\n").encode() + payload
        )
        await writer.drain()

    # -- routing ------------------------------------------------------------

    async def _route(self, method, path, body, writer) -> None:
        if path == "/healthz":
            if method != "GET":
                raise _HTTPError(405, "healthz is GET")
            stats = await self._router_call(self.router.stats)
            await self._send_json(writer, 200, stats)
        elif path == "/v1/generate":
            if method != "POST":
                raise _HTTPError(405, "generate is POST")
            await self._generate(body or {}, writer)
        elif path == "/v1/abort":
            if method != "POST":
                raise _HTTPError(405, "abort is POST")
            await self._abort(body or {}, writer)
        elif path == "/v1/session/open":
            if method != "POST":
                raise _HTTPError(405, "session/open is POST")
            try:
                sid = await self._router_call(self.router.open_session)
            except RouterOverloaded as err:
                retry = err.retry_after_s or 1.0
                await self._send_json(
                    writer, 429,
                    {"error": str(err), "retry_after_s": retry},
                    extra_headers=(f"Retry-After: {math.ceil(retry)}",),
                )
                return
            except ValueError as err:  # non-paged fleet rejects loudly
                raise _HTTPError(400, str(err)) from None
            await self._send_json(writer, 200, {"session": sid})
        elif path == "/v1/session/close":
            sid = (body or {}).get("session")
            if method != "POST":
                raise _HTTPError(405, "session/close is POST")
            if not isinstance(sid, int):
                raise _HTTPError(400, "close needs an integer session")
            try:
                await self._router_call(self.router.close_session, sid)
            except ValueError as err:  # unknown sid
                raise _HTTPError(404, str(err)) from None
            await self._send_json(
                writer, 200, {"session": sid, "closed": True}
            )
        elif path.startswith("/admin/"):
            if method != "POST":
                raise _HTTPError(405, "admin actions are POST")
            await self._admin(path[len("/admin/"):], body or {}, writer)
        else:
            raise _HTTPError(404, f"no route for {path}")

    def _submit_kwargs(self, body: dict) -> tuple[np.ndarray, int, dict]:
        prompt = body.get("prompt")
        if not isinstance(prompt, list) or not prompt or not all(
            isinstance(t, int) for t in prompt
        ):
            raise _HTTPError(
                400, "prompt must be a non-empty list of token ids"
            )
        max_new = int(body.get("max_new_tokens", self.default_max_new))
        kw: dict = {}
        for k in ("temperature", "top_k", "top_p", "eos_id", "timeout_s",
                  "priority", "tenant", "session"):
            if body.get(k) is not None:
                kw[k] = body[k]
        if "session" in kw and not isinstance(kw["session"], int):
            raise _HTTPError(
                400, "session must be an integer sid from "
                     "POST /v1/session/open"
            )
        if "priority" in kw and not isinstance(kw["priority"], str):
            raise _HTTPError(
                400, "priority must be one of "
                     "'interactive'/'standard'/'batch'"
            )
        if kw.get("temperature"):
            # "seed" is optional on the wire: a sampled request without
            # one draws a fresh seed here rather than surfacing the
            # engine's key= requirement (an argument the HTTP API does
            # not expose).
            import os

            import jax

            seed = body.get("seed")
            if seed is None:
                seed = int.from_bytes(os.urandom(4), "little")
            kw["key"] = jax.random.key(int(seed))
        return np.asarray(prompt, np.int32), max_new, kw

    async def _generate(self, body, writer) -> None:
        prompt, max_new, kw = self._submit_kwargs(body)
        try:
            rid = await self._router_call(
                self.router.submit, prompt, max_new, **kw
            )
        except RouterOverloaded as err:
            retry = err.retry_after_s or 1.0
            await self._send_json(
                writer, 429,
                {"error": str(err), "retry_after_s": retry},
                extra_headers=(f"Retry-After: {math.ceil(retry)}",),
            )
            return
        except ValueError as err:  # bad budgets/args reject loudly
            raise _HTTPError(400, str(err)) from None
        ev = asyncio.Event()
        self._done_events[rid] = ev
        self._work_event.set()
        if body.get("stream"):
            await self._stream_sse(rid, len(prompt), writer)
        else:
            await ev.wait()
            res = await self._router_call(self.router.pop_result, rid)
            await self._send_json(writer, 200, self._result_json(res))

    def _result_json(self, res) -> dict:
        return {
            "rid": int(res.rid),
            "state": res.state,
            "tokens": [int(t) for t in np.asarray(res.tokens)],
            "reason": res.reason,
        }

    async def _stream_sse(self, rid: int, prompt_len: int,
                          writer) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n"
        )
        sent = prompt_len
        try:
            while True:
                tokens = await self._router_call(self.router.progress, rid)
                done = await self._router_call(
                    lambda: rid in self.router.results
                )
                if tokens is not None:
                    for t in np.asarray(tokens)[sent:]:
                        writer.write(
                            f"data: {json.dumps({'token': int(t)})}\n\n"
                            .encode()
                        )
                    sent = max(sent, len(tokens))
                    await writer.drain()  # raises if the client left
                if done:
                    res = await self._router_call(
                        self.router.pop_result, rid
                    )
                    # Flush the tail from the RESULT itself: the
                    # request may have finished between the progress
                    # read above and the done check, and every
                    # generated token owes the client one data event.
                    final = np.asarray(res.tokens)
                    for t in final[sent:]:
                        writer.write(
                            f"data: {json.dumps({'token': int(t)})}\n\n"
                            .encode()
                        )
                    writer.write(
                        ("event: done\ndata: "
                         + json.dumps(self._result_json(res))
                         + "\n\n").encode()
                    )
                    await writer.drain()
                    return
                # Wait for the next scheduler tick (or the idle poll —
                # a parked/queued rid makes no progress between ticks).
                tick = self._tick_event
                try:
                    await asyncio.wait_for(tick.wait(), 0.25)
                except (asyncio.TimeoutError, TimeoutError):
                    pass
        except (ConnectionResetError, BrokenPipeError):
            # Client hung up mid-stream: abort the request — the row
            # frees, the partial result delivers and is discarded.
            try:
                aborted = await self._router_call(self.router.abort, rid)
                if aborted or rid in self.router.results:
                    await self._router_call(self.router.pop_result, rid)
            except KeyError:
                pass
        finally:
            self._done_events.pop(rid, None)

    async def _abort(self, body, writer) -> None:
        rid = body.get("rid")
        if not isinstance(rid, int):
            raise _HTTPError(400, "abort needs an integer rid")
        try:
            aborted = await self._router_call(self.router.abort, rid)
        except KeyError as err:
            raise _HTTPError(404, str(err)) from None
        if aborted:
            # abort() delivers the terminal result directly (outside a
            # step tick), so the drive loop will never signal it — wake
            # any handler blocked on this rid ourselves.
            ev = self._done_events.pop(rid, None)
            if ev is not None:
                ev.set()
        await self._send_json(writer, 200, {"rid": rid, "aborted": aborted})

    async def _admin(self, action: str, body, writer) -> None:
        replica = body.get("replica")
        if not isinstance(replica, int):
            raise _HTTPError(400, f"admin/{action} needs an integer replica")
        try:
            if action == "kill":
                await self._router_call(self.router.kill, replica)
            elif action == "drain":
                await self._router_call(
                    self.router.drain, replica,
                    migrate=bool(body.get("migrate", False)),
                )
            elif action == "restart":
                await self._router_call(
                    self.router.restart, replica, self.params
                )
            else:
                raise _HTTPError(404, f"unknown admin action {action!r}")
        except (RuntimeError, IndexError) as err:
            raise _HTTPError(400, str(err)) from None
        self._work_event.set()
        states = await self._router_call(self.router.replica_states)
        await self._send_json(
            writer, 200, {"action": action, "replica": replica,
                          "states": states},
        )
