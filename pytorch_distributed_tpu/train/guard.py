"""Traced anomaly guard for the training step.

Real training runs die of bad steps, not just bad machines: a corrupt
shard, a numerically unlucky batch, or a NaN-producing kernel poisons
the params, and every step after it is wasted compute. The classic
defence — device_get the loss every step and check it on the host —
serializes dispatch (the host waits for step N before submitting N+1)
and costs real throughput. This guard instead runs INSIDE the compiled
step:

- **Detection is traced.** Three predicates, all computed where the
  values already live: (1) non-finite loss or gradient norm (the NaN/Inf
  sentinel — training's twin of the serving engines' logits sentinel),
  (2) an EMA loss-spike check (``loss > spike_factor * ema`` once the
  EMA has ``warmup_steps`` clean samples), (3) token ids outside
  ``[0, vocab)`` in the batch (corrupt data would otherwise be silently
  clamped by the embedding gather and train on garbage).
- **The reaction is a traced no-op.** On an anomalous step the params
  and optimizer state are carried through UNCHANGED (`jnp.where` per
  leaf); the step counter still advances (it counts consumed data
  windows). The guard state (EMA + counters) rides ``TrainState`` so
  everything is one pure ``(state, batch, key) -> (state, metrics)``
  function: detection adds **zero host syncs per step** and can never
  recompile — there is ONE program with the anomaly select inside it.
- **Policy is host-side, at the existing sync.** The host reads the
  counters at the log-window boundary (where it already device_gets the
  window's losses) and at save boundaries (which sync anyway). After
  ``rollback_after`` CONSECUTIVE anomalies the sticky ``trip`` flag is
  set (traced — a burst entirely inside one window cannot be missed)
  and the Trainer rolls back to the last good checkpoint
  (train/trainer.py), optionally skipping the offending data window.

The guard adds no collectives (all three predicates reduce values the
step already materializes), pinned by the ``train_guard`` audit case
(analysis/registry.py). See docs/ROBUSTNESS.md §9.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp


class GuardState(NamedTuple):
    """Anomaly-guard carry, a few scalars riding TrainState.guard.

    ``ema``/``seen``: exponential moving average of CLEAN losses and how
    many were folded in (the spike check stays off until ``seen``
    reaches the warmup). ``consecutive``: current run of anomalous
    steps (resets on a clean one). ``total``: anomalies since this
    state was initialised (or restored). ``trip``: sticky 0/1, set the
    moment ``consecutive`` reaches the rollback threshold — the host's
    rollback signal, impossible to miss between syncs."""

    ema: jax.Array  # f32 scalar
    seen: jax.Array  # i32 scalar
    consecutive: jax.Array  # i32 scalar
    total: jax.Array  # i32 scalar
    trip: jax.Array  # i32 scalar (sticky 0/1)


def init_guard_state() -> GuardState:
    return GuardState(
        ema=jnp.zeros((), jnp.float32),
        seen=jnp.zeros((), jnp.int32),
        consecutive=jnp.zeros((), jnp.int32),
        total=jnp.zeros((), jnp.int32),
        trip=jnp.zeros((), jnp.int32),
    )


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Static guard parameters (compiled into the step — one program per
    config, never per anomaly). Built from TrainConfig by the Trainer
    (``guard_config_from``)."""

    spike_factor: float = 3.0
    ema_decay: float = 0.98
    warmup_steps: int = 10
    # Consecutive anomalies that set the sticky ``trip`` flag (the host
    # rollback signal). None: never trip — the guard still skips
    # anomalous updates, it just never asks for a rollback.
    rollback_after: int | None = 3
    # Validate token ids against [0, vocab) (0 disables the data check).
    vocab_size: int = 0

    def __post_init__(self) -> None:
        if self.spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {self.spike_factor}"
            )
        if not 0.0 < self.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in (0, 1), got {self.ema_decay}"
            )
        if self.warmup_steps < 1:
            raise ValueError(
                f"warmup_steps must be >= 1, got {self.warmup_steps}"
            )
        if self.rollback_after is not None and self.rollback_after < 1:
            raise ValueError(
                f"rollback_after must be >= 1 or None, got "
                f"{self.rollback_after}"
            )


def guard_config_from(train_cfg, model_cfg) -> GuardConfig | None:
    """The TrainConfig -> GuardConfig adapter (None when the guard is
    off). Lives here so every trainer front-end builds the same guard."""
    if not train_cfg.anomaly_guard:
        return None
    return GuardConfig(
        spike_factor=train_cfg.guard_spike_factor,
        ema_decay=train_cfg.guard_ema_decay,
        warmup_steps=train_cfg.guard_warmup_steps,
        rollback_after=train_cfg.guard_rollback_after,
        vocab_size=model_cfg.vocab_size,
    )


def check_batch(batch: dict, vocab_size: int) -> jax.Array:
    """Traced corrupt-data sentinel: True when any token id in the batch
    falls outside ``[0, vocab_size)``. Without this, a corrupt shard's
    garbage ids are silently clamped by the embedding gather and the
    model trains on noise."""
    bad = jnp.zeros((), jnp.bool_)
    for x in (batch["inputs"], batch["targets"]):
        bad = bad | jnp.any((x < 0) | (x >= vocab_size))
    return bad


def guard_step(
    guard: GuardState,
    loss: jax.Array,
    grad_norm: jax.Array,
    bad_data: jax.Array,
    cfg: GuardConfig,
) -> tuple[GuardState, jax.Array]:
    """One traced guard update: classify this step, fold a clean loss
    into the EMA, advance the counters. Returns (new_guard, anomaly)."""
    nonfinite = ~jnp.isfinite(loss) | ~jnp.isfinite(grad_norm)
    warmed = guard.seen >= cfg.warmup_steps
    spike = warmed & (loss > cfg.spike_factor * guard.ema)
    anomaly = nonfinite | spike | bad_data
    clean = ~anomaly

    loss32 = loss.astype(jnp.float32)
    first = guard.seen == 0
    folded = jnp.where(
        first, loss32, cfg.ema_decay * guard.ema
        + (1.0 - cfg.ema_decay) * loss32,
    )
    new_ema = jnp.where(clean, folded, guard.ema)
    new_seen = guard.seen + clean.astype(jnp.int32)
    new_consecutive = jnp.where(
        anomaly, guard.consecutive + 1, jnp.zeros((), jnp.int32)
    )
    new_total = guard.total + anomaly.astype(jnp.int32)
    if cfg.rollback_after is not None:
        new_trip = guard.trip | (
            new_consecutive >= cfg.rollback_after
        ).astype(jnp.int32)
    else:
        new_trip = guard.trip
    return (
        GuardState(new_ema, new_seen, new_consecutive, new_total, new_trip),
        anomaly,
    )


def apply_guard(anomaly: jax.Array, new_tree, old_tree):
    """Select the pre-step tree on anomaly, the updated one otherwise —
    leafwise ``where``, so the update is a traced no-op (same program,
    same shapes, nothing to recompile)."""
    return jax.tree.map(
        lambda n, o: jnp.where(anomaly, o, n), new_tree, old_tree
    )
