"""Training loop with gradient accumulation, logging, checkpoint/resume.

Capability twin of reference train/trainer.py:9-141 (Trainer) — same
responsibilities, TPU-native shape:

- ONE jitted ``train_step(state, batch, key) -> (state, metrics)`` containing
  the whole optimizer step; gradient accumulation is a ``lax.scan`` over
  micro-batches *inside* jit (reference does a Python loop of
  ``(loss/grad_acc).backward()`` calls, trainer.py:49-61,82-88). The scan
  keeps HLO size independent of the accumulation factor and naturally matches
  DDP no_sync semantics later: gradients are only combined at the boundary.
- loss is averaged over micro-batches (≡ reference's 1/grad_acc loss scaling).
- periodic logging of avg loss / lr / elapsed (reference :92-98), periodic
  checkpointing (reference :100-106), optional profiler stepped once per
  optimizer step (the reference steps per micro-batch, trainer.py:111-113;
  with accumulation fused into one XLA computation the optimizer step is the
  natural host-visible unit — the profiler schedule counts those instead).
- checkpoint/resume restores {params, opt_state, step}
  (reference :117-141).
"""

from __future__ import annotations

import contextlib
import math
import time
from pathlib import Path
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pytorch_distributed_tpu.config import ModelConfig, TrainConfig
from pytorch_distributed_tpu.models import ModelApi
from pytorch_distributed_tpu.ops.losses import (
    cross_entropy_loss,
    linear_cross_entropy,
)
from pytorch_distributed_tpu.train import checkpoint as ckpt_lib
from pytorch_distributed_tpu.train import guard as guard_lib
from pytorch_distributed_tpu.train.guard import GuardConfig, guard_config_from
from pytorch_distributed_tpu.train.optim import lr_at_step, make_optimizer
from pytorch_distributed_tpu.train.state import TrainState, init_train_state
from pytorch_distributed_tpu.utils.logging import get_logger
from pytorch_distributed_tpu.utils.prng import domain_key, step_key


def make_train_step(
    model: ModelApi,
    model_cfg: ModelConfig,
    tx: optax.GradientTransformation,
    *,
    donate: bool = True,
    jit: bool = True,
    logits_sharding=None,
    grad_shardings=None,
    accum_dtype: str = "float32",
    guard: GuardConfig | None = None,
) -> Callable:
    """Build the jitted (state, batch, dropout_key) -> (state, metrics) step.

    ``batch`` is a dict with "inputs"/"targets" of shape [A, B, T] where A is
    the accumulation factor (A=1 means no accumulation). Gradients are
    averaged over the A micro-batches before one optimizer update.

    ``guard`` (train/guard.py) compiles the traced anomaly guard into the
    step: non-finite loss/grad + EMA loss-spike + corrupt-token-id
    detection, with the update selected to a no-op on anomaly and the
    counters carried in ``state.guard`` — one program, zero per-step host
    syncs, zero steady-state recompiles. Requires ``state.guard`` to be an
    initialised GuardState (Trainer.init_state does this).

    ``logits_sharding``/``grad_shardings`` (mesh runs only): sharding
    constraints pinned on the [B, T, V] logits and the gradient pytree.
    Without them XLA's SPMD partitioner can pick mismatched shardings for the
    cross-entropy backward and the gradient accumulator under a tensor-
    parallel mesh and fall back to "involuntary full rematerialization" —
    replicating logits-sized tensors (see parallel/api.py, which passes both).
    """
    train_mode = (
        model_cfg.embd_pdrop > 0
        or model_cfg.attn_pdrop > 0
        or model_cfg.resid_pdrop > 0
    )

    def constrain_grads(grads):
        if grad_shardings is None:
            return grads
        return jax.tree.map(
            jax.lax.with_sharding_constraint, grads, grad_shardings
        )

    def micro_loss(params, inputs, targets, key):
        fused = model_cfg.fused_head_ce
        out = model.apply(
            params,
            inputs,
            model_cfg,
            deterministic=not train_mode,
            dropout_key=key,
            return_aux=bool(model_cfg.n_experts),
            return_hidden=fused,
        )
        out, aux = out if model_cfg.n_experts else (out, 0.0)
        if fused:
            # Head matmul fused into the loss: no [B, T, V] logits tensor
            # (ops/losses.linear_cross_entropy). logits_sharding does not
            # apply — there are no logits to constrain.
            hidden = out
            w, layout = model.head_weight(params)
            loss = linear_cross_entropy(
                hidden.reshape(-1, hidden.shape[-1]),
                w,
                targets.reshape(-1),
                w_layout=layout,
                logits_dtype=model_cfg.logits_dtype,
            )
        else:
            logits = out
            if logits_sharding is not None:
                logits = jax.lax.with_sharding_constraint(
                    logits, logits_sharding
                )
            loss = cross_entropy_loss(logits, targets)
        if model_cfg.n_experts:
            # Switch load-balancing term (ops/moe.py).
            loss = loss + model_cfg.moe_aux_coef * aux
        return loss

    grad_fn = jax.value_and_grad(micro_loss)

    def step_fn(state: TrainState, batch: dict, dropout_key: jax.Array):
        accum = batch["inputs"].shape[0]

        if accum == 1:
            # No accumulation: skip the scan and the f32 zero-grad buffers
            # (their extra HBM round-trip is measurable at small step times).
            loss, grads = grad_fn(
                state.params,
                batch["inputs"][0],
                batch["targets"][0],
                jax.random.fold_in(dropout_key, 0),
            )
            grads = constrain_grads(grads)
        else:

            def scan_body(carry, xs):
                grads_acc, loss_acc = carry
                inputs, targets, idx = xs
                key = jax.random.fold_in(dropout_key, idx)
                loss, grads = grad_fn(state.params, inputs, targets, key)
                grads_acc = constrain_grads(
                    jax.tree.map(
                        # Accumulate in the buffer's dtype (accum_dtype):
                        # plain + would promote bf16 buffers back to f32.
                        lambda a, g: a + g.astype(a.dtype), grads_acc, grads
                    )
                )
                return (grads_acc, loss_acc + loss), None

            zeros = constrain_grads(
                jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.dtype(accum_dtype)),
                    state.params,
                )
            )
            (grads, loss_sum), _ = jax.lax.scan(
                scan_body,
                (zeros, jnp.zeros((), jnp.float32)),
                (batch["inputs"], batch["targets"], jnp.arange(accum)),
            )
            grads = jax.tree.map(lambda g: g / accum, grads)
            loss = loss_sum / accum

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
        }
        new_guard = state.guard
        if guard is not None:
            bad_data = (
                guard_lib.check_batch(batch, guard.vocab_size)
                if guard.vocab_size
                else jnp.zeros((), jnp.bool_)
            )
            new_guard, anomaly = guard_lib.guard_step(
                state.guard, loss, metrics["grad_norm"], bad_data, guard
            )
            # Anomalous step -> traced no-op update: params AND optimizer
            # state carried through unchanged (the step counter still
            # advances — it counts consumed data windows).
            new_params = guard_lib.apply_guard(
                anomaly, new_params, state.params
            )
            new_opt_state = guard_lib.apply_guard(
                anomaly, new_opt_state, state.opt_state
            )
            metrics["anomaly"] = anomaly
        return (
            TrainState(new_params, new_opt_state, state.step + 1, new_guard),
            metrics,
        )

    if not jit:
        return step_fn
    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def make_eval_step(
    model: ModelApi, model_cfg: ModelConfig, *, jit: bool = True
) -> Callable:
    """Build the jitted (params, batch) -> loss evaluation step.

    Deterministic forward (no dropout) + the same cross-entropy as
    training (fused when cfg.fused_head_ce). ``batch`` holds
    "inputs"/"targets" of shape [B, T]. The reference downloads a
    fineweb validation shard (reference data/data_loader.py:28-41) but
    never evaluates on it; this closes that loop.
    """

    def eval_fn(params, batch):
        inputs, targets = batch["inputs"], batch["targets"]
        if inputs.ndim == 3:  # [A, B, T] (mesh-placed) -> [A*B, T]
            inputs = inputs.reshape(-1, inputs.shape[-1])
            targets = targets.reshape(-1, targets.shape[-1])
        if model_cfg.fused_head_ce:
            hidden = model.apply(
                params, inputs, model_cfg, return_hidden=True
            )
            w, layout = model.head_weight(params)
            return linear_cross_entropy(
                hidden.reshape(-1, hidden.shape[-1]),
                w,
                targets.reshape(-1),
                w_layout=layout,
                logits_dtype=model_cfg.logits_dtype,
            )
        logits = model.apply(params, inputs, model_cfg)
        return cross_entropy_loss(logits, targets)

    # repolint: allow(jit-donation-decision) — eval reads params the
    # training loop still owns; donating them would free live state.
    return jax.jit(eval_fn) if jit else eval_fn


class Trainer:
    """Single-device (or single-sharding-context) training driver.

    Args mirror the reference Trainer (reference train/trainer.py:9-47):
    grad-accum factor from global/micro batch sizes, log/save cadences. The
    data loader yields [B, T] (inputs, targets) host batches; the trainer
    groups ``accum`` of them into one [A, B, T] device batch per step.
    """

    def __init__(
        self,
        model: ModelApi,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        data_parallel_size: int = 1,
        put_batch: Callable[[dict], dict] | None = None,
        train_step: Callable | None = None,
        log_fn: Callable[[str], None] | None = None,
    ):
        self.model = model
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.accum = train_cfg.grad_accum_steps(data_parallel_size)
        self.tx = make_optimizer(train_cfg)
        self.guard_cfg = guard_config_from(train_cfg, model_cfg)
        self.train_step = (
            train_step
            if train_step is not None
            else make_train_step(
                model, model_cfg, self.tx,
                accum_dtype=train_cfg.accum_dtype,
                guard=self.guard_cfg,
            )
        )
        self._put_batch = put_batch or (lambda b: b)
        self._dropout_root = domain_key(train_cfg.seed, "dropout")
        self._log = log_fn or get_logger().info
        self._injector = None  # train/chaos.TrainFaultInjector (or None)

    def set_fault_injector(self, injector) -> None:
        """Install a train/chaos.TrainFaultInjector (or None to remove):
        host-side hooks at the step/save boundaries — nothing traced ever
        sees it (docs/ROBUSTNESS.md §11)."""
        self._injector = injector

    # -- state ------------------------------------------------------------
    def init_state(self, init_key: jax.Array | None = None) -> TrainState:
        key = (
            init_key
            if init_key is not None
            else domain_key(self.train_cfg.seed, "init")
        )
        params = self.model.init(key, self.model_cfg)
        g = (
            guard_lib.init_guard_state()
            if self.guard_cfg is not None
            else None
        )
        return init_train_state(params, self.tx, guard=g)

    # -- checkpointing (reference trainer.py:100-141) ---------------------
    def checkpoint_path(self, step: int) -> Path:
        return Path(self.train_cfg.checkpoint_dir) / f"checkpoint_step_{step}"

    def save_checkpoint(
        self, state: TrainState, *, loader: Any | None = None
    ) -> str:
        step = int(jax.device_get(state.step))
        metadata: dict = {"step": step}
        if loader is not None and hasattr(loader, "state_dict"):
            # Data-stream position rides the checkpoint so resumed runs
            # continue the token stream instead of repeating it (the
            # reference's loader always restarts at shard 0).
            metadata["loader_state"] = loader.state_dict()
        if self.train_cfg.async_checkpoint:
            # Fire-and-forget: the write overlaps subsequent steps; the
            # previous in-flight save is finalized first (inside
            # save_checkpoint_async), and train() finalizes the last one.
            path = ckpt_lib.save_checkpoint_async(
                self.checkpoint_path(step), state, metadata=metadata
            )
            if self.train_cfg.keep_checkpoints is not None:
                # The PREVIOUS save just became visible — prune now so
                # disk stays bounded during the run, not only at its end.
                # (prune_checkpoints itself excludes the in-flight save's
                # target, so the fire-and-forget write is never raced.)
                ckpt_lib.prune_checkpoints(
                    self.train_cfg.checkpoint_dir,
                    self.train_cfg.keep_checkpoints,
                )
            self._after_save()
            return path
        path = ckpt_lib.save_checkpoint(
            self.checkpoint_path(step),
            state,
            metadata=metadata,
        )
        if self.train_cfg.keep_checkpoints is not None:
            # After the (barriered) save: only strictly-older dirs go.
            ckpt_lib.prune_checkpoints(
                self.train_cfg.checkpoint_dir,
                self.train_cfg.keep_checkpoints,
            )
        self._after_save()
        return path

    def _after_save(self) -> None:
        if self._injector is not None:
            self._injector.after_save(self.train_cfg.checkpoint_dir)

    def load_checkpoint(self, path: str | Path, state: TrainState) -> TrainState:
        return ckpt_lib.load_checkpoint(path, state)

    def _load_latest_good(
        self, state: TrainState
    ) -> tuple[TrainState, str] | None:
        """Walk the retained checkpoints newest-first and load the first
        one that passes integrity verification, logging every corrupt
        candidate skipped. None when no checkpoints exist; raises
        ``CheckpointCorrupt`` when checkpoints exist but ALL fail (a
        silent from-scratch restart would be data loss)."""
        candidates = ckpt_lib.list_checkpoints(self.train_cfg.checkpoint_dir)
        if not candidates:
            stray = ckpt_lib.uncommitted_checkpoints(
                self.train_cfg.checkpoint_dir
            )
            if stray:
                # Checkpoint-shaped dirs with no COMMIT marker: half-
                # written saves, or the pre-integrity on-disk format.
                # Starting over next to them must not look like a clean
                # first run.
                names = ", ".join(Path(s).name for s in stray[:3])
                self._log(
                    f"WARNING: no committed checkpoint in "
                    f"{self.train_cfg.checkpoint_dir}, but {len(stray)} "
                    f"checkpoint dir(s) without a COMMIT marker exist "
                    f"({names}{', ...' if len(stray) > 3 else ''}): "
                    "half-written saves or pre-integrity-format "
                    "checkpoints — not resumable; training starts fresh"
                )
            return None
        for path in candidates:
            try:
                return self.load_checkpoint(path, state), path
            except ckpt_lib.CheckpointCorrupt as e:
                self._log(
                    f"checkpoint {path} failed integrity verification "
                    f"({e}); falling back to the next-older retained "
                    "checkpoint"
                )
        raise ckpt_lib.CheckpointCorrupt(
            f"all {len(candidates)} retained checkpoints in "
            f"{self.train_cfg.checkpoint_dir} failed verification"
        )

    def resume_latest(
        self, state: TrainState, *, loader: Any | None = None
    ) -> TrainState:
        # An in-flight async save is invisible until finalized.
        ckpt_lib.finalize_async_save()
        loaded = self._load_latest_good(state)
        if loaded is None:
            return state
        restored, path = loaded
        self._log(f"resuming from {path}")
        if loader is not None and hasattr(loader, "load_state_dict"):
            meta = ckpt_lib.read_metadata(path)
            if "loader_state" in meta:
                loader.load_state_dict(meta["loader_state"])
        return restored

    def _guard_rollback(self, state: TrainState, dataloader, groups):
        """The guard tripped (guard_rollback_after consecutive anomalies):
        restore the newest loadable checkpoint, rewind the data stream to
        its position (unless guard_skip_window — the policy for
        persistent data corruption), and continue. Returns the restored
        (state, groups, step). Raises loudly when no checkpoint is
        loadable or guard_max_rollbacks is exhausted — a thrashing run
        must fail, not spin."""
        cfg = self.train_cfg
        self._rollbacks += 1
        if self._rollbacks > cfg.guard_max_rollbacks:
            raise RuntimeError(
                f"anomaly guard rolled back {cfg.guard_max_rollbacks} "
                "times in one run and tripped again — the anomaly is "
                "persistent; inspect the data/numerics (or set "
                "guard_skip_window=True for corrupt-data streams)"
            )
        ckpt_lib.finalize_async_save()
        loaded = self._load_latest_good(state)
        if loaded is None:
            raise RuntimeError(
                "anomaly guard tripped but no checkpoint exists to roll "
                "back to; set save_every_n_steps (or disable rollback "
                "with guard_rollback_after=None)"
            )
        restored, path = loaded
        rewound = False
        if not cfg.guard_skip_window:
            meta = ckpt_lib.read_metadata(path)
            if hasattr(dataloader, "load_state_dict") and (
                "loader_state" in meta
            ):
                dataloader.load_state_dict(meta["loader_state"])
                groups = self._grouped_batches(dataloader)
                rewound = True
        new_step = int(jax.device_get(restored.step))
        self._log(
            f"anomaly guard tripped: rolled back to {path} (step "
            f"{new_step}, rollback {self._rollbacks}/"
            f"{cfg.guard_max_rollbacks}); data stream "
            + ("rewound and replayed" if rewound else
               "NOT rewound — offending window skipped")
        )
        return restored, groups, new_step

    # -- data grouping ----------------------------------------------------
    def _grouped_batches(self, dataloader: Iterable):
        """Group ``accum`` [B,T] micro-batches into one [A,B,T] step batch."""
        inputs_buf: list[np.ndarray] = []
        targets_buf: list[np.ndarray] = []
        for inputs, targets in dataloader:
            inputs_buf.append(np.asarray(inputs))
            targets_buf.append(np.asarray(targets))
            if len(inputs_buf) == self.accum:
                yield {
                    "inputs": np.stack(inputs_buf),
                    "targets": np.stack(targets_buf),
                }
                inputs_buf, targets_buf = [], []
        # A trailing partial group is dropped, matching the reference, whose
        # optimizer only steps on complete accumulation windows
        # (trainer.py:82-88).

    # -- the loop (reference trainer.py:63-115) ---------------------------
    def train(
        self,
        dataloader: Iterable,
        *,
        state: TrainState | None = None,
        profiler: Any | None = None,
        num_steps: int | None = None,
    ) -> tuple[TrainState, list[dict]]:
        cfg = self.train_cfg
        if state is None:
            state = self.init_state()
        num_steps = num_steps if num_steps is not None else cfg.num_steps
        start_step = int(jax.device_get(state.step))

        history: list[dict] = []
        # Per-step losses stay ON DEVICE until a log boundary: a device_get
        # every step would serialize dispatch (the host waits for step N
        # before submitting N+1), which costs real throughput at small step
        # times. The reference syncs per log interval in the same spirit
        # (reference train/trainer.py:92-98). The step counter is tracked
        # host-side for the same reason.
        window_losses: list[jax.Array] = []
        t0 = time.perf_counter()
        step = start_step

        preempted = {"flag": False}
        restore_handlers: list = []
        if cfg.save_on_preemption:
            import signal

            def _on_signal(signum, frame):
                preempted["flag"] = True

            try:
                for sig in (signal.SIGTERM, signal.SIGINT):
                    restore_handlers.append(
                        (sig, signal.signal(sig, _on_signal))
                    )
            except ValueError:
                restore_handlers = []  # not the main thread: no handlers

        def stop_requested(*, force_sync: bool = False) -> bool:
            # Multi-host: the signal lands on individual processes at
            # different step boundaries; all processes must agree on ONE
            # stop step or the collective checkpoint save deadlocks. The
            # allgather runs at the same loop point on every process (all
            # processes hold the same `step` under lockstep loaders), so
            # OR-ing the flags yields a common decision. Syncs are gated to
            # every preemption_sync_every_n_steps — in between, the local
            # flag is DEFERRED (not acted on) so no process breaks alone.
            if not cfg.save_on_preemption:
                return False
            if jax.process_count() > 1:
                n = max(1, cfg.preemption_sync_every_n_steps)
                if not force_sync and step % n != 0:
                    return False  # defer to the next common sync point
                from jax.experimental import multihost_utils

                flags = multihost_utils.process_allgather(
                    np.asarray(preempted["flag"])
                )
                return bool(np.any(flags))
            return preempted["flag"]

        # Explicit iterator: the stop check must happen BEFORE fetching the
        # next batch group, or the saved loader position skips data the
        # resumed run never trains on.
        groups = self._grouped_batches(dataloader)
        self._rollbacks = 0
        try:
            while step < num_steps:
              if stop_requested():
                  break  # checkpoint happens once, after the loop
              batch = next(groups, None)
              if batch is None:
                  break
              if self._injector is not None:
                  # Host-side chaos hooks (train/chaos.py): arm this
                  # step's faults, then let the injector crash/signal/
                  # poison BEFORE dispatch — the compiled step only ever
                  # sees a (possibly corrupt) batch, exactly like
                  # production.
                  self._injector.on_step(step + 1)
                  batch = self._injector.before_step(step + 1, batch)
              dkey = step_key(self._dropout_root, step)
              ctx = (
                  profiler.step_context(step)
                  if profiler is not None and hasattr(profiler, "step_context")
                  else contextlib.nullcontext()
              )
              with ctx:
                  state, metrics = self.train_step(
                      state, self._put_batch(batch), dkey
                  )

              window_losses.append(metrics["loss"])
              step = new_step = step + 1

              if profiler is not None:
                  profiler.step()

              if new_step % cfg.log_every_n_steps == 0 or new_step == num_steps:
                  losses = [
                      float(x) for x in jax.device_get(window_losses)
                  ]  # single sync point for the whole window
                  elapsed = time.perf_counter() - t0
                  lr = lr_at_step(cfg, new_step)
                  entry = {
                      "step": new_step,
                      "lr": lr,
                      "elapsed_s": elapsed,
                  }
                  if self.guard_cfg is not None:
                      # The guard counters ride the SAME sync the window
                      # losses already pay — reading them here adds no
                      # per-step cost. Non-finite (skipped) losses are
                      # excluded from the window average so one NaN step
                      # does not turn the whole window's log line NaN.
                      g = jax.device_get(state.guard)
                      finite = [x for x in losses if math.isfinite(x)]
                      avg_loss = (
                          sum(finite) / len(finite)
                          if finite
                          else float("nan")
                      )
                      entry["anomalies"] = int(g.total)
                      suffix = (
                          f" | anomalies {int(g.total)}"
                          if int(g.total)
                          else ""
                      )
                  else:
                      avg_loss = sum(losses) / len(losses)
                      suffix = ""
                  entry["loss"] = avg_loss
                  self._log(
                      f"step {new_step}/{num_steps} | loss {avg_loss:.4f} | "
                      f"lr {lr:.2e} | elapsed {elapsed:.1f}s{suffix}"
                  )
                  history.append(entry)
                  self._write_metrics(entry)
                  window_losses = []
                  if self.guard_cfg is not None and int(g.trip):
                      state, groups, step = self._guard_rollback(
                          state, dataloader, groups
                      )
                      continue

              if (
                  cfg.save_every_n_steps
                  and new_step % cfg.save_every_n_steps == 0
              ):
                  if self.guard_cfg is not None:
                      # A checkpoint must never capture un-adjudicated
                      # anomalies: a later rollback would land on a state
                      # that silently missed the poisoned window's clean
                      # replay. The save already syncs, so this read is
                      # free.
                      g = jax.device_get(state.guard)
                      if int(g.trip):
                          window_losses = []
                          state, groups, step = self._guard_rollback(
                              state, dataloader, groups
                          )
                          continue
                      if int(g.consecutive) > 0:
                          self._log(
                              f"deferring checkpoint at step {new_step}: "
                              f"anomaly burst in progress "
                              f"({int(g.consecutive)} consecutive)"
                          )
                          continue
                  self.save_checkpoint(state, loader=dataloader)
        finally:
            if restore_handlers:
                import signal

                for sig, prev in restore_handlers:
                    signal.signal(sig, prev)
            if cfg.async_checkpoint:
                # Exception-safe durability: an in-flight async save is
                # only committed by finalize; losing it on a raised step
                # or KeyboardInterrupt would silently discard a
                # fully-written checkpoint (idempotent — the normal path
                # below finalizes the preemption save too).
                ckpt_lib.finalize_async_save()
        # NOT short-circuited on the local flag: every process must run the
        # same number of stop_requested() collectives, and must join the
        # collective save when ANY process was signalled. force_sync: this
        # final decision always syncs (exactly once per process) even when
        # the in-loop cadence is gated, so a signal deferred past the last
        # loop iteration is still honoured.
        if self.guard_cfg is not None and step < num_steps:
            # The loop ended early (data exhausted / stop requested)
            # between boundaries: a pending trip would otherwise vanish
            # without adjudication. There is no data left to replay, so
            # the honest move is to say so loudly — the last good
            # checkpoint is the trustworthy resume point.
            g_exit = jax.device_get(state.guard)
            if int(g_exit.trip) or int(g_exit.consecutive):
                self._log(
                    f"WARNING: training ended at step {step} with "
                    f"un-adjudicated anomalies (consecutive "
                    f"{int(g_exit.consecutive)}, trip {int(g_exit.trip)}): "
                    "the returned state skipped anomalous windows without "
                    "rollback; resume from the last good checkpoint to "
                    "replay them"
                )
        if cfg.save_on_preemption and stop_requested(force_sync=True):
            skip_save = False
            if self.guard_cfg is not None:
                # Same clean-history rule as the in-loop save gating: a
                # preemption checkpoint carrying un-adjudicated anomalies
                # (skipped update, trip pending) would anchor every later
                # resume on a state that silently lost the poisoned
                # window's replay. Resume from the last GOOD checkpoint
                # instead — correctness over a few replayed steps.
                g = jax.device_get(state.guard)
                if int(g.trip) or int(g.consecutive):
                    ckpt_lib.finalize_async_save()
                    prior = ckpt_lib.latest_checkpoint(
                        cfg.checkpoint_dir
                    )
                    # Skip ONLY when a good checkpoint exists to resume
                    # from — an anomaly-tainted checkpoint still beats
                    # losing the whole run.
                    skip_save = prior is not None
                    if skip_save:
                        self._log(
                            f"preemption checkpoint at step {step} "
                            f"SKIPPED: un-adjudicated anomalies "
                            f"(consecutive {int(g.consecutive)}, trip "
                            f"{int(g.trip)}); resume replays from "
                            f"{prior}"
                        )
                    else:
                        self._log(
                            f"WARNING: preemption checkpoint at step "
                            f"{step} carries un-adjudicated anomalies "
                            f"(consecutive {int(g.consecutive)}, trip "
                            f"{int(g.trip)}) — saved anyway, no earlier "
                            "checkpoint exists; the skipped windows "
                            "were not replayed"
                        )
            if not skip_save:
                self._log(
                    f"preemption signal received: checkpointing at step "
                    f"{step}"
                )
                self.save_checkpoint(state, loader=dataloader)

        if cfg.async_checkpoint:
            # Durability boundary: the last in-flight save must be
            # committed and visible before train() returns.
            ckpt_lib.finalize_async_save()
            if cfg.keep_checkpoints is not None:
                ckpt_lib.prune_checkpoints(
                    cfg.checkpoint_dir, cfg.keep_checkpoints
                )

        return state, history

    def _write_metrics(self, entry: dict) -> None:
        """Append one JSON line to cfg.metrics_path (if set). Gated to
        process 0 by the DistributedTrainer's log gating convention —
        only where _log would print."""
        path = self.train_cfg.metrics_path
        if not path or not self._is_metrics_writer():
            return
        import json

        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with p.open("a") as f:
            f.write(json.dumps(entry) + "\n")

    def _is_metrics_writer(self) -> bool:
        return True  # DistributedTrainer overrides with process-0 gating

    # -- evaluation -------------------------------------------------------
    def evaluate(
        self,
        state: TrainState,
        dataloader: Iterable,
        *,
        max_batches: int | None = None,
    ) -> float:
        """Mean loss over a validation loader ([B, T] batches), with the
        deterministic forward. Losses stay on device until one final sync."""
        if not hasattr(self, "_eval_step"):
            self._eval_step = make_eval_step(self.model, self.model_cfg)
        losses: list[jax.Array] = []
        for i, (inputs, targets) in enumerate(dataloader):
            if max_batches is not None and i >= max_batches:
                break
            # [1, B, T] so mesh-aware put_batch functions (rank-3 batch
            # sharding) work unchanged; eval_fn flattens the lead axis.
            batch = self._put_batch(
                {"inputs": inputs[None], "targets": targets[None]}
            )
            losses.append(self._eval_step(state.params, batch))
        if not losses:
            raise ValueError("evaluate() got an empty dataloader")
        vals = [float(x) for x in jax.device_get(losses)]
        return sum(vals) / len(vals)
