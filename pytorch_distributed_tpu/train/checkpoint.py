"""Checkpoint save/load for arbitrary train-state pytrees.

Capability twin of reference train/trainer.py:117-141 (torch.save/load of
{model, optimizer, step, lr_scheduler} state dicts): here the unit is the
whole TrainState pytree ({params, opt_state, step} — the LR schedule is a
pure function of step, so it needs no separate state).

Format: one ``.npz`` with flattened leaves keyed by their tree path, plus a
``meta.json`` sidecar with the structure and metadata. Self-contained numpy —
readable without JAX — and path-keyed, so checkpoints survive refactors that
reorder (but not rename) the tree. Save is atomic (write temp dir, rename).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_checkpoint(
    directory: str | Path, state: Any, *, metadata: dict | None = None
) -> str:
    """Serialise a pytree of arrays. Only the calling process writes
    (callers gate on process 0, reference distributed_trainer.py:214-221)."""
    directory = Path(directory)
    os.makedirs(directory.parent if directory.suffix else directory.parent, exist_ok=True)
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {}
    for path, leaf in leaves_with_paths:
        arrays[_path_str(path)] = np.asarray(jax.device_get(leaf))

    tmp = Path(tempfile.mkdtemp(dir=directory.parent, prefix=".ckpt_tmp_"))
    try:
        np.savez(tmp / "arrays.npz", **arrays)
        meta = {
            "format": "pdtpu-ckpt-v1",
            "keys": sorted(arrays.keys()),
            "metadata": metadata or {},
        }
        (tmp / "meta.json").write_text(json.dumps(meta, indent=1))
        if directory.exists():
            shutil.rmtree(directory)
        os.replace(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return str(directory)


def load_checkpoint(directory: str | Path, like: Any) -> Any:
    """Restore into the structure of ``like`` (a template pytree, e.g. a
    freshly initialised TrainState — the analogue of load_state_dict
    restoring into constructed modules, reference trainer.py:130-141)."""
    directory = Path(directory)
    with np.load(directory / "arrays.npz") as data:
        arrays = {k: data[k] for k in data.files}
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = _path_str(path)
        if key not in arrays:
            raise KeyError(
                f"checkpoint {directory} missing leaf {key!r}; "
                f"has {len(arrays)} leaves"
            )
        got = arrays[key]
        want_shape = tuple(leaf.shape)
        if tuple(got.shape) != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {got.shape} != expected {want_shape}"
            )
        new_leaves.append(got.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def read_metadata(directory: str | Path) -> dict:
    meta = json.loads((Path(directory) / "meta.json").read_text())
    return meta.get("metadata", {})


def latest_checkpoint(checkpoint_root: str | Path) -> str | None:
    """Find the newest ``checkpoint_step_{n}`` dir (reference naming
    trainer.py:100-106)."""
    root = Path(checkpoint_root)
    if not root.exists():
        return None
    best, best_step = None, -1
    for child in root.iterdir():
        if child.is_dir() and child.name.startswith("checkpoint_step_"):
            try:
                step = int(child.name.rsplit("_", 1)[1])
            except ValueError:
                continue
            if step > best_step:
                best, best_step = str(child), step
    return best
