"""Checkpoint save/load for arbitrary train-state pytrees.

Capability twin of reference train/trainer.py:117-141 (torch.save/load of
{model, optimizer, step, lr_scheduler} state dicts): here the unit is the
whole TrainState pytree ({params, opt_state, step} — the LR schedule is a
pure function of step, so it needs no separate state).

Two formats behind one API (``save_checkpoint``/``load_checkpoint`` pick by
what the state needs; ``format=`` overrides):

- ``npz``: one ``.npz`` with flattened leaves keyed by their tree path plus a
  ``meta.json`` sidecar. Self-contained numpy — readable without JAX — and
  path-keyed, so checkpoints survive refactors that reorder (but not rename)
  the tree. Save is atomic (write temp dir, rename). SINGLE-HOST ONLY: it
  device_gets every leaf, which throws on a pod where sharded leaves are not
  fully addressable from one process.
- ``orbax``: tensorstore/OCDBT via orbax — every process writes exactly its
  addressable shards and restore places shards directly onto the target
  shardings (the idiomatic multi-host path, SURVEY.md §5.4; the reference's
  rank-0 torch.save, distributed_trainer.py:214-221, is naive here). Used
  automatically when any leaf is not fully addressable.

``load_checkpoint`` restores into the structure AND shardings of the template
pytree: leaves come back as jax.Arrays placed like the template's (the
reference's ``map_location=model.device``, trainer.py:139, generalised to
shardings).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _fully_addressable(state: Any) -> bool:
    for leaf in jax.tree.leaves(state):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
        ):
            return False
    return True


def save_checkpoint(
    directory: str | Path,
    state: Any,
    *,
    metadata: dict | None = None,
    format: str = "auto",
) -> str:
    """Serialise a pytree of arrays.

    format="auto" picks npz when every leaf is addressable from this process
    (single host) and orbax otherwise. npz writes from the calling process
    only (callers gate on process 0, reference distributed_trainer.py:214-221);
    orbax saves are collective — EVERY process must call this, each writes
    its own shards.
    """
    if format == "auto":
        format = "npz" if _fully_addressable(state) else "orbax"
    if format == "orbax":
        return _save_orbax(directory, state, metadata=metadata)
    if format != "npz":
        raise ValueError(f"unknown checkpoint format {format!r}")

    directory = Path(directory)
    if jax.process_count() > 1 and jax.process_index() != 0:
        # npz is a single-writer format; non-zero processes only wait at the
        # barrier so no one races ahead of the write (callers may call this
        # from every process — required for the collective orbax format).
        _sync("pdtpu:ckpt:npz")
        return str(directory)
    os.makedirs(directory.parent, exist_ok=True)
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {}
    for path, leaf in leaves_with_paths:
        arrays[_path_str(path)] = np.asarray(jax.device_get(leaf))

    tmp = Path(tempfile.mkdtemp(dir=directory.parent, prefix=".ckpt_tmp_"))
    try:
        np.savez(tmp / "arrays.npz", **arrays)
        meta = {
            "format": "pdtpu-ckpt-v1",
            "keys": sorted(arrays.keys()),
            "metadata": metadata or {},
        }
        (tmp / "meta.json").write_text(json.dumps(meta, indent=1))
        if directory.exists():
            shutil.rmtree(directory)
        os.replace(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if jax.process_count() > 1:
        _sync("pdtpu:ckpt:npz")
    return str(directory)


def _sync(tag: str) -> None:
    """Cross-process barrier (no-op single-process)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


# One in-flight async save at a time (module-level: the trainer treats
# checkpointing as a global side effect, and two overlapping collective
# saves would interleave their barriers).
_PENDING_ASYNC: dict | None = None


def finalize_async_save() -> str | None:
    """Block until the in-flight async save (if any) commits, then perform
    the tmp -> final swap + metadata write. Returns the finalized path.

    MUST run before: starting another save, reading latest_checkpoint, or
    process exit — Trainer calls it at those points automatically.
    """
    global _PENDING_ASYNC
    if _PENDING_ASYNC is None:
        return None
    pend, _PENDING_ASYNC = _PENDING_ASYNC, None
    pend["ckptr"].wait_until_finished()
    pend["ckptr"].close()
    directory: Path = pend["directory"]
    tmp: Path = pend["tmp"]
    if jax.process_index() == 0:
        (tmp / "meta.json").write_text(
            json.dumps(
                {
                    "format": "pdtpu-ckpt-orbax-v1",
                    "metadata": pend["metadata"],
                },
                indent=1,
            )
        )
        if directory.exists():
            shutil.rmtree(directory)
        os.replace(tmp, directory)
    if jax.process_count() > 1:
        _sync("pdtpu:ckpt:async-final")
    return str(directory)


def save_checkpoint_async(
    directory: str | Path, state: Any, *, metadata: dict | None = None
) -> str:
    """Start an orbax save that overlaps training: device arrays are
    snapshotted now, the serialization/write runs in background threads,
    and the checkpoint becomes VISIBLE (tmp -> final swap, meta.json) only
    at the next ``finalize_async_save()`` — which this function calls
    first for any previous in-flight save, so at most one save is ever
    pending and callers can fire-and-forget on a cadence.

    Collective like the sync orbax path: EVERY process must call it.
    """
    import orbax.checkpoint as ocp

    global _PENDING_ASYNC
    finalize_async_save()
    directory = Path(directory).resolve()
    tmp = directory.parent / (".tmp_" + directory.name)
    directory.parent.mkdir(parents=True, exist_ok=True)
    if jax.process_index() == 0 and tmp.exists():
        shutil.rmtree(tmp)
    if jax.process_count() > 1:
        # No process may start writing before the stale tmp is gone.
        _sync("pdtpu:ckpt:async-clean")
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(tmp / "tree", state)
    _PENDING_ASYNC = {
        "ckptr": ckptr,
        "tmp": tmp,
        "directory": directory,
        "metadata": metadata or {},
    }
    return str(directory)


def _save_orbax(
    directory: str | Path, state: Any, *, metadata: dict | None = None
) -> str:
    import orbax.checkpoint as ocp

    directory = Path(directory).resolve()
    # Write into a deterministic sibling temp dir (same name on every
    # process), then swap. Orbax's collective save is itself atomic into the
    # temp location and returns only once all processes have committed, so
    # the previous checkpoint is deleted only AFTER the new one is complete
    # — a crash in the swap window leaves the new data recoverable at the
    # temp path rather than destroying both.
    tmp = directory.parent / (".tmp_" + directory.name)
    if jax.process_index() == 0 and tmp.exists():
        shutil.rmtree(tmp)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp / "tree", state)
    if jax.process_index() == 0:
        (tmp / "meta.json").write_text(
            json.dumps(
                {"format": "pdtpu-ckpt-orbax-v1", "metadata": metadata or {}},
                indent=1,
            )
        )
        if directory.exists():
            shutil.rmtree(directory)
        os.replace(tmp, directory)
    if jax.process_count() > 1:
        # All processes wait for the swap: no one may act on the returned
        # path (or start a next save reusing tmp) while the rename is in
        # flight on process 0.
        _sync("pdtpu:ckpt:orbax")
    return str(directory)


def load_checkpoint(directory: str | Path, like: Any) -> Any:
    """Restore into the structure AND shardings of ``like`` (a template
    pytree, e.g. a freshly initialised — possibly sharded — TrainState; the
    analogue of load_state_dict restoring into constructed modules,
    reference trainer.py:130-141, with map_location generalised to
    shardings)."""
    directory = Path(directory)
    if (directory / "tree").exists():
        return _load_orbax(directory, like)
    with np.load(directory / "arrays.npz") as data:
        arrays = {k: data[k] for k in data.files}
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = _path_str(path)
        if key not in arrays:
            raise KeyError(
                f"checkpoint {directory} missing leaf {key!r}; "
                f"has {len(arrays)} leaves"
            )
        got = arrays[key]
        want_shape = tuple(leaf.shape)
        if tuple(got.shape) != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {got.shape} != expected {want_shape}"
            )
        restored = got.astype(leaf.dtype)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            # Re-apply the template's placement (sharded restore).
            restored = jax.device_put(restored, leaf.sharding)
        new_leaves.append(restored)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _load_orbax(directory: str | Path, like: Any) -> Any:
    import orbax.checkpoint as ocp

    def abstract(leaf):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=leaf.sharding
            )
        return leaf

    template = jax.tree.map(abstract, like)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(
            Path(directory).resolve() / "tree",
            ocp.args.PyTreeRestore(
                template,
                restore_args=ocp.checkpoint_utils.construct_restore_args(
                    template
                ),
            ),
        )


def read_metadata(directory: str | Path) -> dict:
    meta = json.loads((Path(directory) / "meta.json").read_text())
    return meta.get("metadata", {})


def prune_checkpoints(checkpoint_root: str | Path, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` ``checkpoint_step_{n}`` dirs.

    Process-0 only (other processes no-op); call AFTER a successful save —
    the collective save's own barrier guarantees no peer is still writing
    the surviving checkpoints, and deleted ones are strictly older than
    the one just committed. Returns the removed paths.
    """
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if jax.process_count() > 1 and jax.process_index() != 0:
        return []
    root = Path(checkpoint_root)
    if not root.exists():
        return []
    steps: list[tuple[int, Path]] = []
    for child in root.iterdir():
        if child.is_dir() and child.name.startswith("checkpoint_step_"):
            try:
                steps.append((int(child.name.rsplit("_", 1)[1]), child))
            except ValueError:
                continue
    steps.sort(reverse=True)
    removed = []
    for _, path in steps[keep:]:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(str(path))
    return removed


def latest_checkpoint(checkpoint_root: str | Path) -> str | None:
    """Find the newest ``checkpoint_step_{n}`` dir (reference naming
    trainer.py:100-106)."""
    root = Path(checkpoint_root)
    if not root.exists():
        return None
    best, best_step = None, -1
    for child in root.iterdir():
        if child.is_dir() and child.name.startswith("checkpoint_step_"):
            try:
                step = int(child.name.rsplit("_", 1)[1])
            except ValueError:
                continue
            if step > best_step:
                best, best_step = str(child), step
    return best
