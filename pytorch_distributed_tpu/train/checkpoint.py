"""Checkpoint save/load for arbitrary train-state pytrees.

Capability twin of reference train/trainer.py:117-141 (torch.save/load of
{model, optimizer, step, lr_scheduler} state dicts): here the unit is the
whole TrainState pytree ({params, opt_state, step} — the LR schedule is a
pure function of step, so it needs no separate state).

Two formats behind one API (``save_checkpoint``/``load_checkpoint`` pick by
what the state needs; ``format=`` overrides):

- ``npz``: one ``.npz`` with flattened leaves keyed by their tree path plus a
  ``meta.json`` sidecar. Self-contained numpy — readable without JAX — and
  path-keyed, so checkpoints survive refactors that reorder (but not rename)
  the tree. SINGLE-HOST ONLY: it device_gets every leaf, which throws on a
  pod where sharded leaves are not fully addressable from one process.
- ``orbax``: tensorstore/OCDBT via orbax — every process writes exactly its
  addressable shards and restore places shards directly onto the target
  shardings (the idiomatic multi-host path, SURVEY.md §5.4; the reference's
  rank-0 torch.save, distributed_trainer.py:214-221, is naive here). Used
  automatically when any leaf is not fully addressable.

**Integrity contract** (every format): a save writes a checksum
``manifest.json`` (per-LEAF crc32 for npz, per-FILE crc32 for orbax) and
an atomic ``COMMIT`` marker, all inside a temp dir that is renamed into
place in one atomic step (the old checkpoint is parked in a ``.trash_``
sibling during the swap, so no crash window destroys both generations).
``latest_checkpoint``/``list_checkpoints`` only ever return COMMITTED
directories — a crash mid-save can no longer produce a directory resume
will pick — and ``load_checkpoint`` verifies the manifest first, raising
``CheckpointCorrupt`` on any mismatch (bit rot, torn writes, truncation).
``Trainer.resume_latest`` catches it and falls back to the next-older
retained checkpoint. Crash-anywhere behavior is regression-tested by
killing saves mid-write (tests/test_train_chaos.py) and stormed by
scripts/train_supervisor.py.

``load_checkpoint`` restores into the structure AND shardings of the template
pytree: leaves come back as jax.Arrays placed like the template's (the
reference's ``map_location=model.device``, trainer.py:139, generalised to
shardings).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import zlib
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np

COMMIT_NAME = "COMMIT"
MANIFEST_NAME = "manifest.json"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint directory fails its integrity contract: missing
    COMMIT marker, missing/unreadable payload, or a checksum mismatch
    against its manifest."""


# Host-side fault hook for crash testing: called with (stage, directory)
# at the instant before a save becomes visible (``pre_commit``). The
# training fault injector (train/chaos.py) uses it to kill saves
# mid-write; None in production.
_SAVE_HOOK: Callable[[str, Path], None] | None = None


def set_save_hook(hook: Callable[[str, Path], None] | None) -> None:
    global _SAVE_HOOK
    _SAVE_HOOK = hook


def _fire_save_hook(stage: str, directory: Path) -> None:
    if _SAVE_HOOK is not None:
        _SAVE_HOOK(stage, directory)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _fully_addressable(state: Any) -> bool:
    for leaf in jax.tree.leaves(state):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
        ):
            return False
    return True


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _file_crc(path: Path) -> int:
    """Streaming crc32 — multi-GB tensorstore files must not be held
    wholly in RAM just to checksum them."""
    crc = 0
    with path.open("rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(chunk, crc)


def _file_crcs(root: Path) -> dict[str, int]:
    """crc32 of every regular file under ``root``, keyed by POSIX
    relative path (the orbax/tensorstore payload manifest)."""
    out: dict[str, int] = {}
    for p in sorted(root.rglob("*")):
        if p.is_file():
            out[p.relative_to(root).as_posix()] = _file_crc(p)
    return out


def _commit_orbax(tmp: Path, directory: Path, metadata: dict) -> None:
    """The shared orbax publish tail (sync save AND async finalize):
    meta.json, checksum manifest, COMMIT marker, atomic swap. One
    implementation so the on-disk integrity format cannot fork."""
    meta_text = json.dumps(
        {"format": "pdtpu-ckpt-orbax-v1", "metadata": metadata},
        indent=1,
    )
    (tmp / "meta.json").write_text(meta_text)
    (tmp / MANIFEST_NAME).write_text(
        json.dumps(
            {
                "format": "pdtpu-ckpt-manifest-v1",
                "meta_crc32": _crc32(meta_text.encode()),
                "files": _file_crcs(tmp / "tree"),
            },
            indent=1,
        )
    )
    _write_commit(tmp)
    _swap_into_place(tmp, directory)


def _write_commit(tmp: Path) -> None:
    (tmp / COMMIT_NAME).write_text('{"format": "pdtpu-ckpt-commit-v1"}\n')


def _swap_into_place(tmp: Path, directory: Path) -> None:
    """Atomically publish ``tmp`` as ``directory``. The previous
    generation is parked in a ``.trash_`` sibling for the swap (a crash
    between the two renames leaves the OLD data recoverable there and no
    half directory at the final name) and removed after."""
    trash = directory.parent / (".trash_" + directory.name)
    if trash.exists():
        shutil.rmtree(trash)
    _fire_save_hook("pre_commit", directory)
    if directory.exists():
        os.replace(directory, trash)
    os.replace(tmp, directory)
    shutil.rmtree(trash, ignore_errors=True)


def save_checkpoint(
    directory: str | Path,
    state: Any,
    *,
    metadata: dict | None = None,
    format: str = "auto",
) -> str:
    """Serialise a pytree of arrays.

    format="auto" picks npz when every leaf is addressable from this process
    (single host) and orbax otherwise. npz writes from the calling process
    only (callers gate on process 0, reference distributed_trainer.py:214-221);
    orbax saves are collective — EVERY process must call this, each writes
    its own shards.
    """
    if format == "auto":
        format = "npz" if _fully_addressable(state) else "orbax"
    if format == "orbax":
        return _save_orbax(directory, state, metadata=metadata)
    if format != "npz":
        raise ValueError(f"unknown checkpoint format {format!r}")

    directory = Path(directory)
    if jax.process_count() > 1 and jax.process_index() != 0:
        # npz is a single-writer format; non-zero processes only wait at the
        # barrier so no one races ahead of the write (callers may call this
        # from every process — required for the collective orbax format).
        _sync("pdtpu:ckpt:npz")
        return str(directory)
    os.makedirs(directory.parent, exist_ok=True)
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(state)[0]
    arrays = {}
    for path, leaf in leaves_with_paths:
        arrays[_path_str(path)] = np.asarray(jax.device_get(leaf))

    tmp = Path(tempfile.mkdtemp(dir=directory.parent, prefix=".ckpt_tmp_"))
    try:
        np.savez(tmp / "arrays.npz", **arrays)
        meta_text = json.dumps(
            {
                "format": "pdtpu-ckpt-v1",
                "keys": sorted(arrays.keys()),
                "metadata": metadata or {},
            },
            indent=1,
        )
        (tmp / "meta.json").write_text(meta_text)
        manifest = {
            "format": "pdtpu-ckpt-manifest-v1",
            # meta.json carries the loader position — rot there would
            # silently resume on wrong data, so it is covered too.
            "meta_crc32": _crc32(meta_text.encode()),
            "leaves": {
                k: {
                    "crc32": _crc32(np.ascontiguousarray(a).tobytes()),
                    "shape": list(a.shape),
                    "dtype": str(a.dtype),
                }
                for k, a in arrays.items()
            },
        }
        (tmp / MANIFEST_NAME).write_text(json.dumps(manifest, indent=1))
        _write_commit(tmp)
        _swap_into_place(tmp, directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if jax.process_count() > 1:
        _sync("pdtpu:ckpt:npz")
    return str(directory)


def _sync(tag: str) -> None:
    """Cross-process barrier (no-op single-process)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


# One in-flight async save at a time (module-level: the trainer treats
# checkpointing as a global side effect, and two overlapping collective
# saves would interleave their barriers).
_PENDING_ASYNC: dict | None = None


def pending_async_directory() -> Path | None:
    """Target directory of the in-flight async save, if any — exposed so
    ``prune_checkpoints`` can never race the save it belongs to."""
    return None if _PENDING_ASYNC is None else _PENDING_ASYNC["directory"]


def finalize_async_save() -> str | None:
    """Block until the in-flight async save (if any) commits, then perform
    the tmp -> final swap + manifest/metadata/COMMIT write. Returns the
    finalized path.

    MUST run before: starting another save, reading latest_checkpoint, or
    process exit — Trainer calls it at those points automatically.
    """
    global _PENDING_ASYNC
    if _PENDING_ASYNC is None:
        return None
    pend, _PENDING_ASYNC = _PENDING_ASYNC, None
    pend["ckptr"].wait_until_finished()
    pend["ckptr"].close()
    directory: Path = pend["directory"]
    tmp: Path = pend["tmp"]
    if jax.process_index() == 0:
        # The checksums are computed over the files orbax just finished
        # writing — host-side reads at the (already blocking) finalize
        # point, so the async overlap with training is untouched.
        _commit_orbax(tmp, directory, pend["metadata"])
    if jax.process_count() > 1:
        _sync("pdtpu:ckpt:async-final")
    return str(directory)


def save_checkpoint_async(
    directory: str | Path, state: Any, *, metadata: dict | None = None
) -> str:
    """Start an orbax save that overlaps training: device arrays are
    snapshotted now, the serialization/write runs in background threads,
    and the checkpoint becomes VISIBLE (tmp -> final swap, manifest +
    COMMIT + meta.json) only at the next ``finalize_async_save()`` —
    which this function calls first for any previous in-flight save, so
    at most one save is ever pending and callers can fire-and-forget on
    a cadence.

    Collective like the sync orbax path: EVERY process must call it.
    """
    import orbax.checkpoint as ocp

    global _PENDING_ASYNC
    finalize_async_save()
    directory = Path(directory).resolve()
    tmp = directory.parent / (".tmp_" + directory.name)
    directory.parent.mkdir(parents=True, exist_ok=True)
    if jax.process_index() == 0 and tmp.exists():
        shutil.rmtree(tmp)
    if jax.process_count() > 1:
        # No process may start writing before the stale tmp is gone.
        _sync("pdtpu:ckpt:async-clean")
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(tmp / "tree", state)
    _PENDING_ASYNC = {
        "ckptr": ckptr,
        "tmp": tmp,
        "directory": directory,
        "metadata": metadata or {},
    }
    return str(directory)


def _save_orbax(
    directory: str | Path, state: Any, *, metadata: dict | None = None
) -> str:
    import orbax.checkpoint as ocp

    directory = Path(directory).resolve()
    # Write into a deterministic sibling temp dir (same name on every
    # process), then swap. Orbax's collective save is itself atomic into the
    # temp location and returns only once all processes have committed, so
    # the previous checkpoint is parked/deleted only AFTER the new one is
    # complete — a crash in the swap window leaves the new data recoverable
    # at the temp path rather than destroying both.
    tmp = directory.parent / (".tmp_" + directory.name)
    if jax.process_index() == 0 and tmp.exists():
        shutil.rmtree(tmp)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp / "tree", state)
    if jax.process_index() == 0:
        _commit_orbax(tmp, directory, metadata or {})
    if jax.process_count() > 1:
        # All processes wait for the swap: no one may act on the returned
        # path (or start a next save reusing tmp) while the rename is in
        # flight on process 0.
        _sync("pdtpu:ckpt:orbax")
    return str(directory)


def is_committed(directory: str | Path) -> bool:
    return (Path(directory) / COMMIT_NAME).is_file()


def _load_manifest(directory: Path) -> dict:
    """COMMIT + manifest + meta.json checks (the cheap, non-payload part
    of verification); returns the parsed manifest."""
    if not is_committed(directory):
        raise CheckpointCorrupt(
            f"checkpoint {directory} has no {COMMIT_NAME} marker "
            "(half-written save or pre-integrity format)"
        )
    try:
        manifest = json.loads((directory / MANIFEST_NAME).read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointCorrupt(
            f"checkpoint {directory}: unreadable {MANIFEST_NAME}: {e}"
        ) from e
    want_meta = manifest.get("meta_crc32")
    if want_meta is not None:
        try:
            meta_bytes = (directory / "meta.json").read_bytes()
        except OSError as e:
            raise CheckpointCorrupt(
                f"checkpoint {directory}: unreadable meta.json: {e}"
            ) from e
        got = _crc32(meta_bytes)
        if got != want_meta:
            raise CheckpointCorrupt(
                f"checkpoint {directory}: meta.json checksum mismatch "
                f"(manifest {want_meta}, file {got}) — the loader "
                "position would be untrustworthy"
            )
    return manifest


def _load_npz_arrays(directory: Path, *, wrap_errors: bool) -> dict:
    try:
        with np.load(directory / "arrays.npz") as data:
            return {k: data[k] for k in data.files}
    except Exception as e:  # zip/format damage surfaces many ways
        if not wrap_errors:
            raise
        raise CheckpointCorrupt(
            f"checkpoint {directory}: unreadable arrays.npz: {e}"
        ) from e


def _verify_npz_leaves(directory: Path, manifest: dict, arrays: dict) -> None:
    for key, want in manifest["leaves"].items():
        if key not in arrays:
            raise CheckpointCorrupt(
                f"checkpoint {directory}: leaf {key!r} missing from "
                "arrays.npz"
            )
        got = _crc32(np.ascontiguousarray(arrays[key]).tobytes())
        if got != want["crc32"]:
            raise CheckpointCorrupt(
                f"checkpoint {directory}: leaf {key!r} checksum "
                f"mismatch (manifest {want['crc32']}, file {got})"
            )


def verify_checkpoint(directory: str | Path) -> None:
    """Integrity check without a full restore: COMMIT present, manifest
    present, meta.json and every payload checksum matching. Raises
    ``CheckpointCorrupt`` naming the first offending leaf/file."""
    directory = Path(directory)
    manifest = _load_manifest(directory)
    if "leaves" in manifest:
        arrays = _load_npz_arrays(directory, wrap_errors=True)
        _verify_npz_leaves(directory, manifest, arrays)
    else:
        for rel, want in manifest.get("files", {}).items():
            f = directory / "tree" / rel
            if not f.is_file():
                raise CheckpointCorrupt(
                    f"checkpoint {directory}: payload file {rel!r} missing"
                )
            got = _file_crc(f)
            if got != want:
                raise CheckpointCorrupt(
                    f"checkpoint {directory}: payload file {rel!r} "
                    f"checksum mismatch (manifest {want}, file {got})"
                )


def load_checkpoint(
    directory: str | Path, like: Any, *, verify: bool = True
) -> Any:
    """Restore into the structure AND shardings of ``like`` (a template
    pytree, e.g. a freshly initialised — possibly sharded — TrainState; the
    analogue of load_state_dict restoring into constructed modules,
    reference trainer.py:130-141, with map_location generalised to
    shardings). ``verify`` (default) checks the integrity manifest first
    and raises ``CheckpointCorrupt`` on damage (the npz payload is read
    ONCE — checksums are taken on the same arrays the restore uses); pass
    False only for forensics on a checkpoint you know is damaged."""
    directory = Path(directory)
    if (directory / "tree").exists():
        if verify:
            verify_checkpoint(directory)
        return _load_orbax(directory, like)
    if verify:
        manifest = _load_manifest(directory)
        arrays = _load_npz_arrays(directory, wrap_errors=True)
        if "leaves" in manifest:
            _verify_npz_leaves(directory, manifest, arrays)
    else:
        arrays = _load_npz_arrays(directory, wrap_errors=False)
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(like)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = _path_str(path)
        if key not in arrays:
            if key.split("/", 1)[0] == "guard":
                # Pre-guard checkpoint restored into a guard-enabled
                # template (a run upgraded to anomaly_guard mid-life):
                # the counters start fresh — the template's
                # init_guard_state values ARE the right defaults.
                new_leaves.append(leaf)
                continue
            raise KeyError(
                f"checkpoint {directory} missing leaf {key!r}; "
                f"has {len(arrays)} leaves"
            )
        got = arrays[key]
        want_shape = tuple(leaf.shape)
        if tuple(got.shape) != want_shape:
            raise ValueError(
                f"checkpoint leaf {key!r} shape {got.shape} != expected {want_shape}"
            )
        restored = got.astype(leaf.dtype)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            if getattr(leaf, "_committed", True):
                # Re-apply the template's placement (sharded restore).
                restored = jax.device_put(restored, leaf.sharding)
            else:
                # The template is UNCOMMITTED (a plain jit output, the
                # single-device trainer's normal state). device_put would
                # pin the restored leaves and make the next train_step
                # compile a second committed-inputs executable — a
                # restored run must hit the SAME cache entry the
                # uninterrupted run compiled (the zero-steady-state-
                # recompile contract, pinned in tests/test_train_chaos).
                import jax.numpy as jnp

                restored = jnp.asarray(restored)
        new_leaves.append(restored)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _load_orbax(directory: str | Path, like: Any) -> Any:
    import orbax.checkpoint as ocp

    def abstract(leaf):
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=leaf.sharding
            )
        return leaf

    template = jax.tree.map(abstract, like)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(
            Path(directory).resolve() / "tree",
            ocp.args.PyTreeRestore(
                template,
                restore_args=ocp.checkpoint_utils.construct_restore_args(
                    template
                ),
            ),
        )


def read_metadata(directory: str | Path) -> dict:
    meta = json.loads((Path(directory) / "meta.json").read_text())
    return meta.get("metadata", {})


def _step_dirs(root: Path) -> list[tuple[int, Path]]:
    steps: list[tuple[int, Path]] = []
    for child in root.iterdir():
        if child.is_dir() and child.name.startswith("checkpoint_step_"):
            try:
                steps.append((int(child.name.rsplit("_", 1)[1]), child))
            except ValueError:
                continue
    return steps


def _step_dirs_by_commit(
    checkpoint_root: str | Path, *, committed: bool
) -> list[str]:
    root = Path(checkpoint_root)
    if not root.exists():
        return []
    steps = [
        (s, p) for s, p in _step_dirs(root) if is_committed(p) == committed
    ]
    steps.sort(reverse=True)
    return [str(p) for _, p in steps]


def list_checkpoints(checkpoint_root: str | Path) -> list[str]:
    """COMMITTED ``checkpoint_step_{n}`` dirs, newest first — the
    fallback order ``Trainer.resume_latest`` walks when the newest one
    fails verification."""
    return _step_dirs_by_commit(checkpoint_root, committed=True)


def uncommitted_checkpoints(checkpoint_root: str | Path) -> list[str]:
    """``checkpoint_step_{n}`` dirs WITHOUT a COMMIT marker — half-written
    saves, or checkpoints from the pre-integrity format. Never resumable;
    surfaced so ``Trainer.resume_latest`` can warn loudly instead of
    silently restarting from scratch next to them."""
    return _step_dirs_by_commit(checkpoint_root, committed=False)


def prune_checkpoints(checkpoint_root: str | Path, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` ``checkpoint_step_{n}`` dirs
    (and sweep post-swap ``.trash_`` leftovers plus save temp dirs
    orphaned by a hard crash mid-save).

    Process-0 only (other processes no-op); call AFTER a successful save —
    the collective save's own barrier guarantees no peer is still writing
    the surviving checkpoints, and deleted ones are strictly older than
    the one just committed. Never touches the target of an in-flight
    async save (``pending_async_directory``) or any ``.tmp_`` dir it is
    writing. Returns the removed paths.
    """
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    if jax.process_count() > 1 and jax.process_index() != 0:
        return []
    root = Path(checkpoint_root)
    if not root.exists():
        return []
    pending = pending_async_directory()
    pending_tmp = (
        None if pending is None else ".tmp_" + pending.name
    )
    for child in root.iterdir():
        if not child.is_dir():
            continue
        # Post-swap .trash_ parking dirs are garbage the moment the swap
        # is done. Orphaned save temp dirs (a hard crash mid-save skips
        # the in-process cleanup) are garbage too and checkpoint-sized —
        # without this sweep a crash storm grows disk unboundedly. The
        # npz .ckpt_tmp_ dirs are written synchronously by THIS process,
        # so by prune time (always after a completed save) none is live;
        # of the async .tmp_ dirs only the pending save's target is.
        if child.name.startswith((".trash_", ".ckpt_tmp_")) or (
            child.name.startswith(".tmp_") and child.name != pending_tmp
        ):
            shutil.rmtree(child, ignore_errors=True)
    steps = [
        (s, p)
        for s, p in _step_dirs(root)
        if pending is None or p.resolve() != pending
    ]
    steps.sort(reverse=True)
    removed = []
    for _, path in steps[keep:]:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(str(path))
    return removed


def latest_checkpoint(checkpoint_root: str | Path) -> str | None:
    """Find the newest COMMITTED ``checkpoint_step_{n}`` dir (reference
    naming trainer.py:100-106). Half-written directories (no COMMIT
    marker) are never returned — that is the crash-safety contract."""
    newest = list_checkpoints(checkpoint_root)
    return newest[0] if newest else None
