"""Distributed training driver.

Capability twin of reference train/distributed_trainer.py:11-237
(DistributedTrainer), TPU-native:

- world identity from the mesh + ``jax.process_index()`` (reference reads
  RANK/WORLD_SIZE env and hard-fails without init_process_group, :63-79;
  here a Mesh is the proof of initialisation);
- grad-accum factor uses the distributed rule global // (micro * dp_world)
  (reference Task 1, :84-88) via TrainConfig.grad_accum_steps;
- gradient sync happens once per optimizer step at the accumulation
  boundary by construction (the no_sync dance of reference :93-129 is
  unnecessary: collectives are placed after the in-jit accumulation scan);
- the logged loss is already globally averaged (the explicit
  all_reduce(AVG) of reference :131-154 lives in the step function);
- logging and checkpointing are process-0-gated (reference :201-221);
- step timing is device-fenced via block_until_ready on the metrics
  (reference uses cuda.Event pairs + synchronize, :158-163,204-211).

Two step implementations, selected by ``path``:
  "auto"     pjit/NamedSharding — XLA places collectives (parallel/api.py)
  "explicit" shard_map with hand-written psum / all_gather / psum_scatter
             (parallel/explicit.py)
Both are numerically identical to the single-device Trainer (tested).
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from pytorch_distributed_tpu.config import MeshConfig, ModelConfig, TrainConfig
from pytorch_distributed_tpu.models import ModelApi
from pytorch_distributed_tpu.parallel.api import make_parallel_train_step
from pytorch_distributed_tpu.parallel.explicit import make_explicit_train_step
from pytorch_distributed_tpu.parallel.mesh import (
    batch_partition_spec,
    data_parallel_size,
)
from pytorch_distributed_tpu.parallel.sharding import shard_train_state
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.trainer import Trainer
from pytorch_distributed_tpu.utils.logging import get_logger, is_process_zero


class DistributedTrainer(Trainer):
    def __init__(
        self,
        model: ModelApi,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        mesh: Mesh,
        mesh_cfg: MeshConfig,
        *,
        path: str = "auto",
        log_fn: Callable[[str], None] | None = None,
    ):
        if path not in ("auto", "explicit", "pipeline"):
            raise ValueError(f"unknown parallel path {path!r}")
        if path == "pipeline" and mesh_cfg.pipe <= 1:
            raise ValueError("path='pipeline' requires a pipe>1 mesh axis")
        if path == "pipeline" and model_cfg.n_layer % mesh_cfg.pipe:
            raise ValueError(
                f"pipeline stages must divide the layer stack: n_layer="
                f"{model_cfg.n_layer} vs pipe={mesh_cfg.pipe}"
            )
        if train_cfg.anomaly_guard and path != "auto":
            # The guarded update (train/guard.py) rides the trainer/pjit
            # step; the hand-scheduled explicit/pipeline bodies would
            # need their own carry plumbing for the GuardState specs.
            raise ValueError(
                f"anomaly_guard is supported on path='auto' (pjit), not "
                f"path={path!r}"
            )
        self.mesh = mesh
        self.mesh_cfg = mesh_cfg
        self.path = path
        self._batch_sharding = NamedSharding(
            mesh, batch_partition_spec(mesh_cfg)
        )

        def gated_log(msg: str) -> None:
            if is_process_zero():
                (log_fn or get_logger().info)(msg)

        super().__init__(
            model,
            model_cfg,
            train_cfg,
            data_parallel_size=data_parallel_size(mesh_cfg),
            put_batch=self._put_batch_impl,
            train_step=None,  # built lazily once state sharding is known
            log_fn=gated_log,
        )
        self.train_step = None  # type: ignore[assignment]

    # -- state ------------------------------------------------------------
    def init_state(self, init_key=None) -> TrainState:
        """Initialise and shard the train state; builds the parallel step."""
        state = super().init_state(init_key)
        if self.path == "pipeline":
            from pytorch_distributed_tpu.parallel.pipeline import (
                make_pipeline_train_step,
                shard_pipeline_state,
            )

            from pytorch_distributed_tpu.train.optim import make_optimizer

            state, _ = shard_pipeline_state(state, self.mesh, self.mesh_cfg)
            # Clip-free optimizer: the pipeline step clips against the
            # pipe/fsdp-aware psum'd global norm itself (same contract as
            # the explicit path below).
            self.train_step = make_pipeline_train_step(
                self.model, self.model_cfg,
                make_optimizer(self.train_cfg, with_clip=False), self.mesh,
                self.mesh_cfg, state, self.train_cfg,
                schedule=self.mesh_cfg.pipe_schedule,
                grad_clip_norm=self.train_cfg.grad_clip_norm,
            )
            return state
        state, _ = shard_train_state(state, self.mesh, self.mesh_cfg)
        if self.path == "explicit":
            # Clip-free optimizer: optax's clip inside shard_map would see
            # shard-LOCAL grads and compute a different clip scale per shard.
            # The explicit step clips against the psum'd global norm itself.
            from pytorch_distributed_tpu.train.optim import make_optimizer

            self.train_step = make_explicit_train_step(
                self.model, self.model_cfg,
                make_optimizer(self.train_cfg, with_clip=False), self.mesh,
                self.mesh_cfg, state,
                grad_clip_norm=self.train_cfg.grad_clip_norm,
                accum_dtype=self.train_cfg.accum_dtype,
            )
        else:
            self.train_step, _ = make_parallel_train_step(
                self.model, self.model_cfg, self.tx, self.mesh,
                self.mesh_cfg, state,
                accum_dtype=self.train_cfg.accum_dtype,
                guard=self.guard_cfg,
            )
        return state

    def _is_metrics_writer(self) -> bool:
        return is_process_zero()

    # -- data placement ---------------------------------------------------
    def _put_batch_impl(self, batch: dict) -> dict:
        """Host [A, B_local, T] -> global sharded device batch.

        Single-process: B_local is the global batch. Multi-host: each process
        feeds its DistributedTokenShardLoader slice and
        make_array_from_process_local_data assembles the global array — the
        moment the reference crosses with its rank-sliced loader + NCCL
        (SURVEY.md §3.2)."""
        return {
            k: jax.make_array_from_process_local_data(
                self._batch_sharding, np.asarray(v)
            )
            for k, v in batch.items()
        }

    # -- checkpointing ------------------------------------------------------
    def save_checkpoint(self, state: TrainState, *, loader=None) -> str:
        # NOT process-0-gated: every process must call — sharded (orbax)
        # saves are collective (each process writes its own shards; gating
        # would deadlock process 0 inside the commit barrier), and the npz
        # path does its own process-0 write gating internally. This is where
        # the reference's rank-0 torch.save (distributed_trainer.py:214-221)
        # is structurally wrong for sharded state, per SURVEY.md §5.4.
        return super().save_checkpoint(state, loader=loader)

    def train(self, dataloader, *, state=None, profiler=None, num_steps=None):
        if state is None:
            state = self.init_state()
        if self.train_step is None:
            raise RuntimeError("call init_state() before train()")
        return super().train(
            dataloader, state=state, profiler=profiler, num_steps=num_steps
        )
