"""Train state: the single pytree carried through the jitted step.

The reference scatters this state across mutable objects (model params inside
nn.Module, optimizer state inside AdamW, step counter on the Trainer —
reference train/trainer.py:36-47). TPU-natively it is one immutable pytree so
the whole update is a pure function ``(state, batch) -> (state, metrics)``
that jit/pjit can shard end-to-end.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array  # scalar int32
    # Anomaly-guard carry (train/guard.GuardState) when the traced guard
    # is enabled; None otherwise. None is an empty pytree subtree, so
    # guard-off states flatten/checkpoint/shard exactly as before.
    guard: Any = None


def init_train_state(params, tx, *, guard: Any = None) -> TrainState:
    import jax.numpy as jnp

    return TrainState(
        params=params,
        opt_state=tx.init(params),
        step=jnp.zeros((), jnp.int32),
        guard=guard,
    )
