"""Optimizer + LR schedule.

Capability twin of the reference's AdamW(lr=3e-4, wd=0.1) +
CosineAnnealingLR(T_max=num_steps, eta_min=0.1*lr)
(reference train_baseline.py:61-64), built on optax. Weight decay is applied
to all params, matching torch AdamW's default behavior in the reference
(no param-group exclusions there).
"""

from __future__ import annotations

import math

import jax
import optax

from pytorch_distributed_tpu.config import TrainConfig


def make_schedule(cfg: TrainConfig):
    peak = cfg.learning_rate
    floor = cfg.min_lr_ratio * peak
    if cfg.lr_schedule == "constant":
        sched = optax.constant_schedule(peak)
    elif cfg.lr_schedule == "cosine":
        # torch CosineAnnealingLR semantics: lr(t) = floor +
        # (peak-floor) * (1 + cos(pi * t / T_max)) / 2.
        sched = optax.cosine_decay_schedule(
            init_value=peak,
            decay_steps=max(cfg.num_steps, 1),
            alpha=cfg.min_lr_ratio,
        )
    else:
        raise KeyError(f"unknown lr_schedule {cfg.lr_schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def lr_at_step(cfg: TrainConfig, step: int) -> float:
    """Host-side schedule evaluation for logging (reference logs lr from the
    scheduler, train/trainer.py:94-97)."""
    if cfg.warmup_steps > 0 and step < cfg.warmup_steps:
        return cfg.learning_rate * step / cfg.warmup_steps
    t = step - cfg.warmup_steps
    peak, floor = cfg.learning_rate, cfg.min_lr_ratio * cfg.learning_rate
    if cfg.lr_schedule == "constant":
        return peak
    tmax = max(cfg.num_steps, 1)
    frac = min(t / tmax, 1.0)
    return floor + (peak - floor) * 0.5 * (1.0 + math.cos(math.pi * frac))


def make_optimizer(
    cfg: TrainConfig, *, with_clip: bool = True
) -> optax.GradientTransformation:
    """``with_clip=False`` swaps the clip element for ``optax.identity()``
    (same empty state, so opt-state trees stay checkpoint-compatible).
    Callers that run the update inside ``shard_map`` with sharded grads
    (parallel/explicit.py) MUST pass ``with_clip=False`` and clip against
    the psum'd global norm themselves — ``optax.clip_by_global_norm`` seen
    per-shard computes a shard-local norm, a different clip scale per
    shard."""
    if cfg.decay_exclude_1d:
        # Modern convention: no weight decay on norm scales and biases.
        # Matched by NAME (leaf key "bias"/"scale") plus an effective-rank
        # rule that accounts for layer-STACKED block leaves ([L, ...] —
        # an ln scale is [L, E], rank 2, but logically 1-D per layer).
        # Default OFF: the reference decays everything (torch AdamW
        # default, train_baseline.py:61).
        def decay_mask(params):
            def rule(path, p):
                keys = [getattr(k, "key", None) for k in path]
                if keys and keys[-1] in ("bias", "scale"):
                    return False
                eff_ndim = getattr(p, "ndim", 0) - (
                    1 if "blocks" in keys else 0
                )
                return eff_ndim >= 2

            return jax.tree_util.tree_map_with_path(rule, params)

        decay = optax.add_decayed_weights(cfg.weight_decay, mask=decay_mask)
    else:
        decay = optax.add_decayed_weights(cfg.weight_decay)
    steps = [
        optax.clip_by_global_norm(cfg.grad_clip_norm)
        if (with_clip and cfg.grad_clip_norm is not None)
        else optax.identity(),
        optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps),
        decay,
        optax.scale_by_learning_rate(make_schedule(cfg)),
    ]
    return optax.chain(*steps)
