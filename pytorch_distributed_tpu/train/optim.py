"""Optimizer + LR schedule.

Capability twin of the reference's AdamW(lr=3e-4, wd=0.1) +
CosineAnnealingLR(T_max=num_steps, eta_min=0.1*lr)
(reference train_baseline.py:61-64), built on optax. Weight decay is applied
to all params, matching torch AdamW's default behavior in the reference
(no param-group exclusions there).
"""

from __future__ import annotations

import math

import optax

from pytorch_distributed_tpu.config import TrainConfig


def make_schedule(cfg: TrainConfig):
    peak = cfg.learning_rate
    floor = cfg.min_lr_ratio * peak
    if cfg.lr_schedule == "constant":
        sched = optax.constant_schedule(peak)
    elif cfg.lr_schedule == "cosine":
        # torch CosineAnnealingLR semantics: lr(t) = floor +
        # (peak-floor) * (1 + cos(pi * t / T_max)) / 2.
        sched = optax.cosine_decay_schedule(
            init_value=peak,
            decay_steps=max(cfg.num_steps, 1),
            alpha=cfg.min_lr_ratio,
        )
    else:
        raise KeyError(f"unknown lr_schedule {cfg.lr_schedule!r}")
    if cfg.warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        sched = optax.join_schedules([warmup, sched], [cfg.warmup_steps])
    return sched


def lr_at_step(cfg: TrainConfig, step: int) -> float:
    """Host-side schedule evaluation for logging (reference logs lr from the
    scheduler, train/trainer.py:94-97)."""
    if cfg.warmup_steps > 0 and step < cfg.warmup_steps:
        return cfg.learning_rate * step / cfg.warmup_steps
    t = step - cfg.warmup_steps
    peak, floor = cfg.learning_rate, cfg.min_lr_ratio * cfg.learning_rate
    if cfg.lr_schedule == "constant":
        return peak
    tmax = max(cfg.num_steps, 1)
    frac = min(t / tmax, 1.0)
    return floor + (peak - floor) * 0.5 * (1.0 + math.cos(math.pi * frac))


def make_optimizer(
    cfg: TrainConfig, *, with_clip: bool = True
) -> optax.GradientTransformation:
    """``with_clip=False`` swaps the clip element for ``optax.identity()``
    (same empty state, so opt-state trees stay checkpoint-compatible).
    Callers that run the update inside ``shard_map`` with sharded grads
    (parallel/explicit.py) MUST pass ``with_clip=False`` and clip against
    the psum'd global norm themselves — ``optax.clip_by_global_norm`` seen
    per-shard computes a shard-local norm, a different clip scale per
    shard."""
    steps = [
        optax.clip_by_global_norm(cfg.grad_clip_norm)
        if (with_clip and cfg.grad_clip_norm is not None)
        else optax.identity(),
        optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2, eps=cfg.eps),
        optax.add_decayed_weights(cfg.weight_decay),
        optax.scale_by_learning_rate(make_schedule(cfg)),
    ]
    return optax.chain(*steps)
