"""Deterministic fault injection for the training loop.

The training twin of serving/chaos.py, built on the same shared schedule
engine (utils/chaos.ScriptedFaults): seeded + scripted faults fired
through HOST-SIDE hooks at the Trainer's step and save boundaries —
nothing traced ever sees the injector, so injection cannot change the
compiled train step, its shapes, or its pinned (absence-of-)collective
budget. The fault paths exercise the SAME executable production training
runs.

Fault catalog (the full training fault model — docs/ROBUSTNESS.md §11):

- ``crash``        — hard process death at a step boundary
  (``crash_mode="exit"``: ``os._exit`` — no finally blocks, no signal
  handlers, no async-save finalize, exactly like a kill -9 or a machine
  loss) or an in-process ``ChaosCrash`` for tests (``"raise"``). With
  ``program="save"`` the crash lands INSIDE a checkpoint save, the
  instant before it becomes visible — the half-written-checkpoint
  hazard the COMMIT marker exists for.
- ``sigterm``      — SIGTERM to self mid-run: drives the preemption
  path (save_on_preemption) end-to-end — finish the in-flight step,
  checkpoint with loader position, exit.
- ``bad_batch``    — corrupt the next step's host batch (token ids
  forced outside [0, vocab), what a torn shard read actually looks
  like) so the TRACED guard (train/guard.py) must detect and skip it.
  Transient: a replayed window after rollback gets the clean batch.
- ``ckpt_corrupt`` — flip one byte in the newest COMMITTED checkpoint's
  payload (never its COMMIT marker — detection must come from the
  checksum manifest, not from the marker's absence), forcing
  ``resume_latest`` onto the next-older retained checkpoint.
- ``slow_step``    — stall the host between steps (straggler /
  interference model), measured by the supervisor's goodput leg.

``scripts/train_supervisor.py`` storms all of these at once and proves
recovery bit-exact against a fault-free run.
"""

from __future__ import annotations

import json
import os
import signal
import time
from pathlib import Path

import numpy as np

from pytorch_distributed_tpu.train import checkpoint as ckpt_lib
from pytorch_distributed_tpu.utils import chaos as _chaos
from pytorch_distributed_tpu.utils.chaos import (  # noqa: F401  (re-export)
    VirtualClock,
)

TRAIN_FAULT_KINDS = (
    "crash", "sigterm", "bad_batch", "ckpt_corrupt", "slow_step"
)

# The exit status a crash fault dies with (distinct from python's 1 and
# SIGTERM's 143 so the supervisor can attribute restarts).
CRASH_EXIT_CODE = 43


class ChaosCrash(BaseException):
    """In-process form of an injected crash (``crash_mode="raise"``).
    BaseException so library ``except Exception`` blocks can't swallow
    the 'process died' simulation."""


class TrainFault(_chaos.Fault):
    """One scripted training injection. ``tick`` is the 1-based optimizer
    step about to run. ``program`` restricts crash faults to "step"
    (default, fires at the step boundary) or "save" (fires inside the
    checkpoint save, pre-commit)."""

    KINDS = TRAIN_FAULT_KINDS


class TrainFaultInjector(_chaos.ScriptedFaults):
    """Seeded + scripted fault schedule over the Trainer's host hooks.

    ``crash_mode``: "raise" (ChaosCrash — catchable, for in-process
    tests) or "exit" (``os._exit(CRASH_EXIT_CODE)`` — the real thing,
    for the supervisor). ``counts_path``: when set, ``counts`` is
    rewritten there after every firing — a crash fault cannot fire
    without first recording itself, so the supervisor can aggregate
    fault coverage across dead attempts. ``sleep``: how slow_step
    stalls apply (wall ``time.sleep`` by default; pass a VirtualClock's
    ``advance`` for deterministic tests).
    """

    def __init__(
        self,
        faults: tuple[TrainFault, ...] | list[TrainFault] = (),
        *,
        seed: int | None = None,
        p_crash: float = 0.0,
        p_sigterm: float = 0.0,
        p_bad_batch: float = 0.0,
        p_ckpt_corrupt: float = 0.0,
        p_slow_step: float = 0.0,
        slow_step_s: float = 0.05,
        crash_mode: str = "raise",
        bad_token: int = -1,
        counts_path: str | Path | None = None,
        sleep=None,
    ) -> None:
        if crash_mode not in ("raise", "exit"):
            raise ValueError(
                f"unknown crash_mode {crash_mode!r} "
                "(implemented: raise, exit)"
            )
        super().__init__(
            faults,
            seed=seed,
            probabilities={
                "crash": p_crash,
                "sigterm": p_sigterm,
                "bad_batch": p_bad_batch,
                "ckpt_corrupt": p_ckpt_corrupt,
                "slow_step": p_slow_step,
            },
            slow_kinds=("slow_step",),
            slow_s=slow_step_s,
            advance=sleep if sleep is not None else time.sleep,
            fault_cls=TrainFault,
        )
        self._crash_mode = crash_mode
        self._bad_token = int(bad_token)
        self._counts_path = Path(counts_path) if counts_path else None
        self._corrupt_rng = np.random.default_rng(
            seed if seed is not None else 0
        )

    def install(self, trainer) -> "TrainFaultInjector":
        """Wire into a Trainer: step/save-boundary hooks plus the
        checkpoint module's save hook (mid-save crashes)."""
        trainer.set_fault_injector(self)
        ckpt_lib.set_save_hook(self.on_save)
        return self

    # -- trainer hooks (host-side only) -------------------------------------

    def on_step(self, step: int) -> None:
        """Arm this step's faults; slow_step stalls apply immediately."""
        self.on_tick(step)

    def before_step(self, step: int, batch: dict) -> dict:
        """Fire step-boundary faults; returns the (possibly poisoned)
        batch the step will actually train on."""
        if self._pop("crash", "step") is not None:
            self._count("crash")
            self._crash(f"injected crash at step {step}")
        if self._pop("sigterm", "step") is not None:
            self._count("sigterm")
            os.kill(os.getpid(), signal.SIGTERM)
        f = self._pop("bad_batch", "step")
        if f is not None:
            self._count("bad_batch")
            batch = {k: np.array(v, copy=True) for k, v in batch.items()}
            # Corrupt a slice of the first micro-batch's ids — exactly
            # what a torn shard read yields. The traced guard's
            # range check must catch it; nothing host-side tells the
            # step this batch is special.
            flat = batch["inputs"].reshape(-1)
            n = max(1, flat.size // 8)
            flat[:n] = self._bad_token
        return batch

    def on_save(self, stage: str, directory) -> None:
        """Checkpoint-module hook: a ``program="save"`` crash fires the
        instant before the save becomes visible."""
        if stage == "pre_commit" and self._pop("crash", "save") is not None:
            self._count("crash")
            self._crash(f"injected crash mid-save of {directory}")

    def after_save(self, checkpoint_root) -> None:
        """Post-save hook: ckpt_corrupt flips one byte in the newest
        COMMITTED checkpoint's payload."""
        if self._pop("ckpt_corrupt", "step") is None:
            return
        latest = ckpt_lib.latest_checkpoint(checkpoint_root)
        if latest is None:
            return
        target = self._corrupt_target(Path(latest))
        if target is None:
            return
        data = bytearray(target.read_bytes())
        if not data:
            return
        pos = int(self._corrupt_rng.integers(len(data)))
        data[pos] ^= 0xFF
        target.write_bytes(bytes(data))
        self._count("ckpt_corrupt")

    # -- internals -----------------------------------------------------------

    def _corrupt_target(self, ckpt: Path) -> Path | None:
        """Pick a payload file (npz arrays or an orbax tree file) — never
        the COMMIT marker or manifest: detection must come from the
        checksums, the way real bit rot presents."""
        npz = ckpt / "arrays.npz"
        if npz.is_file():
            return npz
        tree = ckpt / "tree"
        if tree.is_dir():
            files = sorted(p for p in tree.rglob("*") if p.is_file())
            if files:
                return files[int(self._corrupt_rng.integers(len(files)))]
        return None

    def _count(self, kind: str) -> None:
        # Overrides the shared hook so EVERY firing — including the base
        # engine's slow_step stalls — is persisted before anything else
        # happens; a crash fault cannot erase the record.
        super()._count(kind)
        if self._counts_path is not None:
            self._counts_path.write_text(json.dumps(self.counts))

    def _crash(self, message: str):
        if self._crash_mode == "exit":
            os._exit(CRASH_EXIT_CODE)
        raise ChaosCrash(message)
