from pytorch_distributed_tpu.train.state import TrainState  # noqa: F401
from pytorch_distributed_tpu.train.optim import make_optimizer, lr_at_step  # noqa: F401
from pytorch_distributed_tpu.train.trainer import Trainer  # noqa: F401
