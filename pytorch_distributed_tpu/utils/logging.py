"""Minimal host-side logging with process-0 gating.

The reference logs via bare ``print`` gated on rank 0
(reference train/distributed_trainer.py:201-212, SURVEY.md §5.5). Here the
process identity comes from ``jax.process_index()`` instead of RANK env vars.
"""

from __future__ import annotations

import logging
import sys

import jax

_CONFIGURED = False


def get_logger(name: str = "pdtpu") -> logging.Logger:
    global _CONFIGURED
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s] %(message)s", "%H:%M:%S")
        )
        root = logging.getLogger("pdtpu")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _CONFIGURED = True
    return logger


def log_event(
    event: str, *, logger: logging.Logger | None = None, **fields
) -> None:
    """One structured lifecycle line: ``event=<name> key=value ...`` with
    keys sorted and Nones dropped, so serving-engine incidents (soak
    failures, chaos runs) are diagnosable — and greppable — from the log
    alone. Emitted at DEBUG on the ``pdtpu.serving`` child logger:
    lifecycle events are per-request bookkeeping, not operator output;
    enable with ``get_logger("pdtpu.serving").setLevel(logging.DEBUG)``
    (scripts/soak.py tees them to a file). Host-side only — never call
    from traced code (repolint's host-sync rule would flag the formatting
    anyway)."""
    lg = logger or get_logger("pdtpu.serving")
    if lg.isEnabledFor(logging.DEBUG):
        parts = [f"event={event}"] + [
            f"{k}={fields[k]}"
            for k in sorted(fields)
            if fields[k] is not None
        ]
        lg.debug(" ".join(parts))


def is_process_zero() -> bool:
    return jax.process_index() == 0


def log_on_process_zero(message: str, logger: logging.Logger | None = None) -> None:
    if is_process_zero():
        (logger or get_logger()).info(message)
