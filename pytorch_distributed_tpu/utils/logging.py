"""Minimal host-side logging with process-0 gating.

The reference logs via bare ``print`` gated on rank 0
(reference train/distributed_trainer.py:201-212, SURVEY.md §5.5). Here the
process identity comes from ``jax.process_index()`` instead of RANK env vars.
"""

from __future__ import annotations

import logging
import sys

import jax

_CONFIGURED = False


def get_logger(name: str = "pdtpu") -> logging.Logger:
    global _CONFIGURED
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s] %(message)s", "%H:%M:%S")
        )
        root = logging.getLogger("pdtpu")
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        root.propagate = False
        _CONFIGURED = True
    return logger


def is_process_zero() -> bool:
    return jax.process_index() == 0


def log_on_process_zero(message: str, logger: logging.Logger | None = None) -> None:
    if is_process_zero():
        (logger or get_logger()).info(message)
