"""Version shims for the varying-manual-axes (vma) shard_map surface.

The codebase is written against the typed shard_map of recent jax:
``jax.typeof`` exposing ``aval.vma``, ``jax.lax.pcast``/``pvary`` to mark
constants varying, and ``shard_map(..., check_vma=True)`` verifying
replication invariants at trace time. On older jax (<= 0.4.x) none of
that exists — the vma TYPE SYSTEM itself is absent — so these shims
degrade to the untyped semantics those versions ship: ``pcast``/``pvary``
become identity (there is no varying-ness to record), ``typeof`` falls
back to ``jax.core.get_aval`` (whose avals carry no ``.vma``, so callers'
``getattr(..., "vma", frozenset())`` defaults engage), and ``shard_map``
maps ``check_vma=True`` onto ``check_rep=False`` — the old replication
CHECKER must be off because it predates the typed-psum patterns this repo
writes (hand-psums of values it would infer replicated).

On new jax every shim is a straight pass-through, so behavior there is
identical to calling the real APIs.
"""

from __future__ import annotations

import inspect

import jax

try:  # stable location since jax 0.6
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def typeof(x):
    """``jax.typeof`` where available, else the aval (no ``.vma``)."""
    if hasattr(jax, "typeof"):
        return jax.typeof(x)
    return jax.core.get_aval(x)


def vma_of(x) -> frozenset:
    """Mesh axes ``x`` is typed varying over (empty on untyped jax)."""
    return frozenset(getattr(typeof(x), "vma", frozenset()))


def pcast_varying(x, axes):
    """Cast ``x`` varying over ``axes`` (identity when empty or untyped)."""
    axes = tuple(axes)
    if not axes:
        return x
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to="varying")
    if hasattr(jax.lax, "pvary"):  # pragma: no cover - mid-era jax
        return jax.lax.pvary(x, axes)
    return x


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """shard_map accepting ``check_vma`` on every jax version."""
    if "check_vma" in _SHARD_MAP_PARAMS:
        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )
