"""Seeded + scripted fault-injection primitives shared across subsystems.

PR 6 built deterministic fault injection for the serving engines
(serving/chaos.py); the training stack needs the identical discipline
(train/chaos.py). The domain-agnostic core lives here so both injectors
are provably the same machinery:

- ``VirtualClock`` — a clock that advances ONLY through injected time
  (backoff sleeps, stall faults), making deadlines/backoff/stalls replay
  exactly run after run.
- ``Fault`` — one scripted injection, optionally validated against a
  domain's fault-kind catalog (subclass and set ``KINDS``).
- ``ScriptedFaults`` — the schedule engine: scripted faults fire exactly
  once at their tick; a seeded schedule draws one Bernoulli per
  (kind, tick) from a private generator so the whole storm is a pure
  function of (seed, tick sequence); "slow" kinds advance the clock
  immediately; every firing is counted in ``counts`` so a run can assert
  its fault schedule actually fired (a chaos test that injected nothing
  is coverage theater).

Domains subclass ``ScriptedFaults`` with their own hook points
(serving: dispatch boundaries; training: step/save boundaries) and their
own kind catalogs. Everything here is HOST-SIDE only — nothing traced
ever sees an injector, so injection cannot change a compiled program,
its shapes, or its pinned collective budgets.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, ClassVar

import numpy as np


class VirtualClock:
    """A deterministic clock: advances ONLY via ``sleep``/``advance``
    (backoff sleeps and slow-tick faults). Pass as both ``clock=`` and
    ``sleep=`` to the consumer so deadlines, backoff, and stalls replay
    identically run after run."""

    def __init__(self, start: float = 0.0) -> None:
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def sleep(self, seconds: float) -> None:
        self.now += max(0.0, float(seconds))

    advance = sleep


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scripted injection. ``tick`` is the consumer's step counter
    (first step = tick 1). ``program`` restricts the fault to one named
    injection point (None = first eligible point of the tick); ``row``
    picks a target index where the domain has one (serving's nan_row
    slot); ``seconds`` is the stall length for slow kinds (None = the
    injector's default). Subclasses set ``KINDS`` to validate ``kind``
    against their catalog at construction."""

    tick: int
    kind: str
    program: str | None = None
    row: int | None = None
    seconds: float | None = None

    KINDS: ClassVar[tuple[str, ...] | None] = None

    def __post_init__(self) -> None:
        if self.KINDS is not None and self.kind not in self.KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {self.KINDS}"
            )


class ScriptedFaults:
    """Seeded + scripted fault schedule over per-tick hooks.

    ``faults``: scripted ``Fault`` list (fires exactly once each).
    ``seed``: enables the random schedule — each tick draws one Bernoulli
    per entry of ``probabilities`` (in insertion order, so the schedule
    is a pure function of the seed and the tick sequence).
    ``slow_kinds``: kinds that stall rather than arm — they advance the
    clock (or call ``advance``) immediately at ``on_tick``.
    ``clock``/``advance``: how slow kinds apply their stall; ``clock``
    (a VirtualClock) keeps the stall deterministic, ``advance`` (e.g. a
    real ``time.sleep``) makes it a wall-clock slowdown.
    ``fault_cls``: the domain's Fault subclass (its ``KINDS`` validates
    seeded draws too, and seeds ``counts``).
    """

    def __init__(
        self,
        faults: tuple[Fault, ...] | list[Fault] = (),
        *,
        seed: int | None = None,
        probabilities: dict[str, float] | None = None,
        slow_kinds: tuple[str, ...] = (),
        slow_s: float = 0.25,
        clock: VirtualClock | None = None,
        advance: Callable[[float], None] | None = None,
        fault_cls: type[Fault] = Fault,
    ) -> None:
        self._scripted: dict[int, list[Fault]] = {}
        for f in faults:
            self._scripted.setdefault(f.tick, []).append(f)
        self._rng = (
            np.random.default_rng(seed) if seed is not None else None
        )
        self._p = dict(probabilities or {})
        self._slow_kinds = tuple(slow_kinds)
        self._slow_s = float(slow_s)
        self._advance = advance if advance is not None else (
            clock.advance if clock is not None else None
        )
        self._fault_cls = fault_cls
        self._armed: list[Fault] = []  # this tick's not-yet-fired faults
        kinds = fault_cls.KINDS if fault_cls.KINDS else tuple(self._p)
        self.counts = {k: 0 for k in kinds}

    # -- schedule engine ----------------------------------------------------

    def on_tick(self, tick: int) -> None:
        """Arm this tick's faults (scripted + seeded draws) and apply
        slow-kind stalls immediately."""
        self._armed = list(self._scripted.pop(tick, ()))
        if self._rng is not None:
            for kind, p in self._p.items():
                if p > 0.0 and self._rng.random() < p:
                    self._armed.append(
                        self._fault_cls(tick, kind, seconds=self._slow_s)
                    )
        for f in [f for f in self._armed if f.kind in self._slow_kinds]:
            self._armed.remove(f)
            if self._advance is None:
                raise ValueError(
                    f"{f.kind} faults need a clock: pass the consumer's "
                    "VirtualClock as clock=... (or a sleep fn as "
                    "advance=...)"
                )
            self._advance(self._slow_s if f.seconds is None else f.seconds)
            self._count(f.kind)

    def _count(self, kind: str) -> None:
        """Record one firing. Subclasses may override to ALSO persist the
        counts externally (the training injector writes them to disk so a
        later crash fault cannot erase the record)."""
        self.counts[kind] += 1

    def _pop(self, kind: str, program: str | None) -> Fault | None:
        """Take (and consume) the first armed fault of ``kind`` whose
        ``program`` restriction matches, if any."""
        for f in self._armed:
            if f.kind == kind and f.program in (None, program):
                self._armed.remove(f)
                return f
        return None
