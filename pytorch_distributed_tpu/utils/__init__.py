from pytorch_distributed_tpu.utils.logging import get_logger, log_on_process_zero  # noqa: F401
from pytorch_distributed_tpu.utils.pytree import (  # noqa: F401
    param_count,
    tree_bytes,
    tree_global_norm,
)
