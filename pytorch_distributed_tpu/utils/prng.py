"""Deterministic PRNG plumbing.

The reference gets cross-rank determinism from a single global
``torch.manual_seed(42)`` on every rank (reference train_ddp.py:73-76). The
JAX-native equivalent is explicit key splitting: one root key derived from the
seed, with named folds for each consumer (init / dropout / data), and per-step
per-layer folds so dropout masks are unique but reproducible.
"""

from __future__ import annotations

import jax


def root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


# Stable small integers for key domains — folded into the root key so that
# adding a new consumer never shifts existing streams.
_DOMAINS = {"init": 0, "dropout": 1, "data": 2, "misc": 3}


def domain_key(seed_or_key: int | jax.Array, domain: str) -> jax.Array:
    key = (
        jax.random.key(seed_or_key)
        if isinstance(seed_or_key, int)
        else seed_or_key
    )
    return jax.random.fold_in(key, _DOMAINS[domain])


def step_key(key: jax.Array, step: jax.Array | int) -> jax.Array:
    """Per-step dropout key: fold the step counter in (traceable under jit)."""
    return jax.random.fold_in(key, step)
