"""Pytree helpers: parameter counting, byte accounting, norms."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def param_count(tree) -> int:
    leaves = jax.tree.leaves(tree)
    return int(sum(int(np.prod(leaf.shape)) for leaf in leaves))


def tree_bytes(tree) -> int:
    leaves = jax.tree.leaves(tree)
    return int(
        sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize for leaf in leaves)
    )


def tree_global_norm(tree) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    )


def tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)
